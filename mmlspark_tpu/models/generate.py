"""Autoregressive generation with a KV cache: the product half of the
long-context LM stack.

The reference has no language model at all (SURVEY §2b headroom), but a
framework that advertises flash/ring-attention training must also produce
tokens.  Design is jit-once / static-shape throughout — the TPU decode
recipe:

  * **prefill**: one full forward over the (fixed-length) prompt writes
    every layer's K/V into a max_len-sized cache and yields the first
    sampled token.  Attention is the ordinary causal batched matmul for
    short prompts (XLA fuses it) and the pallas flash kernel from
    _PREFILL_FLASH_MIN tokens up — a long prompt must not materialize
    the O(P^2) score tensor the flash path exists to avoid.
  * **decode**: a `lax.scan` over step count; each step embeds ONE token,
    updates the caches via `lax.dynamic_update_slice` at a traced
    position, and attends the single query against the full cache under a
    global position mask.  Shapes never change, so the whole generation
    is one compiled program — no per-step dispatch, no retracing, no
    Python in the loop.
  * **sampling**: greedy (temperature 0) or temperature-scaled
    categorical over the top-k / top-p (nucleus) filtered distribution,
    decided at trace time (`filter_logits`).

On top of that per-length recipe sits the **decode engine**
(`DecodeEngine`): the serving path `TextGenerator` actually runs.  Three
compounding optimisations over the one-program-per-prompt-length design:

  * **length-bucketed prefill** — prompts are right-padded to a small set
    of buckets (next power of two, floored at `DEFAULT_MIN_BUCKET`), with
    per-row true-length position ids, attention visibility masks, and a
    per-row last-logit gather, so a ragged workload collapses from one
    compiled program *and one tiny batch per distinct length* into a
    handful of shared shape classes scoring full batches.
  * **cache-windowed decode** — generation runs in segments whose
    compiled scan attends only over a cache *prefix* rounded up to a
    chunk (`decode_segments`); the window grows as the write position
    crosses chunk boundaries, so steady-step bandwidth scales with cache
    occupancy instead of max_len.  Segment programs take the bucket and
    step offsets as traced scalars, so buckets whose windows coincide
    share one compiled segment.
  * **stop-token early exit** — a per-row done mask rides the scan (done
    rows freeze on their stop token) and the engine host-checks `done`
    between segments, so a batch whose rows have all stopped skips the
    remaining segments instead of always paying max_new_tokens steps.

Greedy tokens are exactly those of the per-length decoder (test-pinned
across bucket/window configurations): padding holes are masked to exact
zero weight and positions are per-row, so bucketing is pure layout.
Sampling keys fold in a stable per-row id — a row's draws depend only on
(seed, row id, step), never on how rows were grouped or batched.  Beam
search stays on the full-cache per-length path (windowing lands
sampler-first; see docs/performance.md).

The decoder re-implements the TransformerLM block math as pure functions
over the SAME flax param tree (models/definitions.py names: qkv / proj /
mlp_up / mlp_down / LayerNorm_0/1), so any trained TransformerLM bundle —
including one trained through pipeline parallelism and converted back —
generates without re-exporting weights.  Parity with recompute-everything
decoding is pinned exactly at float32 by tests/test_generate.py for
prompts below _PREFILL_FLASH_MIN (the flash prefill's online softmax can
reassociate near-tie logits above it).  One
deliberate dtype difference: decode attention accumulates QK^T / PV in
float32 (the single-query step is bandwidth-bound, so the extra precision
is free), while the training forward's einsums run in the model dtype —
for bfloat16 bundles the logits agree to bf16 rounding (test-pinned), and
near-tie greedy choices may legitimately resolve differently.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.models.bundle import load_bundle, save_bundle
from mmlspark_tpu.observe.costmodel import capture_program_cost
from mmlspark_tpu.observe.spans import active_timings, span_on
from mmlspark_tpu.observe.telemetry import active_run
from mmlspark_tpu.observe.trace import trace_event, trace_span
from mmlspark_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from mmlspark_tpu.parallel.partition import (
    DRAFT_KV_CACHE_SPEC,
    DRAFT_KV_SCALE_SPEC,
    KV_CACHE_SPEC,
    KV_SCALE_SPEC,
    SEQ_KV_CACHE_SPEC,
    SEQ_KV_SCALE_SPEC,
    shard_constraint,
    use_mesh,
)

NEG_INF = -1e30

# Speculative-decoding RNG streams: disjoint fold_in offsets keep draft
# draws, acceptance coins, and residual/bonus draws off the non-spec
# per-step streams (fold_in(row_key, step), step < max_new_tokens).  A
# row's speculative draws depend only on (its key, round, position) —
# never on batch composition — matching the engine's sampling contract.
_SPEC_DRAFT_STREAM = 1 << 20
_SPEC_COIN_STREAM = 2 << 20
_SPEC_FIX_STREAM = 3 << 20


def _hint_kv(c: jax.Array) -> jax.Array:
    """KV-layout sharding hint: rank-4 (B, W, H, D) payloads carry heads
    on 'model' (KV_CACHE_SPEC); rank-3 (B, W, H) int8-cache scales follow
    (KV_SCALE_SPEC).  Off-mesh the hint is identity (shard_constraint
    degrades), so every decode path stays single-device-portable."""
    if c.ndim == 4:
        return shard_constraint(c, KV_CACHE_SPEC)
    if c.ndim == 3:
        return shard_constraint(c, KV_SCALE_SPEC)
    return c


def _hint_draft_kv(c: jax.Array) -> jax.Array:
    """`_hint_kv` for the DRAFT model's cache: batch on 'data', heads
    replicated (DRAFT_KV_CACHE_SPEC — a latency-sized draft rarely has a
    head count the model axis divides, and its forward is a rounding
    error next to the target's)."""
    if c.ndim == 4:
        return shard_constraint(c, DRAFT_KV_CACHE_SPEC)
    if c.ndim == 3:
        return shard_constraint(c, DRAFT_KV_SCALE_SPEC)
    return c


def _hint_seq_kv(c: jax.Array) -> jax.Array:
    """`_hint_kv` for a SEQ-SHARDED cache: the WINDOW axis splits over
    'seq' (SEQ_KV_CACHE_SPEC / SEQ_KV_SCALE_SPEC) so each chip holds a
    contiguous slab of cache slots — the long-context layout where one
    chip's HBM no longer bounds the window.  Heads stay unsharded (the
    seq engine path refuses model>1 meshes).  Off-mesh the hint is
    identity, same as every other KV hint."""
    if c.ndim == 4:
        return shard_constraint(c, SEQ_KV_CACHE_SPEC)
    if c.ndim == 3:
        return shard_constraint(c, SEQ_KV_SCALE_SPEC)
    return c


def _ln(p: dict, x: jax.Array, dtype) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + 1e-6)
    return (y * p["scale"] + p["bias"]).astype(dtype)


def _dense(p: dict, x: jax.Array, dtype) -> jax.Array:
    if "kernel_scale" in p:
        # int8-quantized kernel (quant/quantize.py layout): int8 weights x
        # low-precision activations with the per-output-channel rescale
        # applied AFTER the matmul — same fused math as quant/modules.py,
        # so int8 TransformerLM bundles decode without a re-export
        y = (x.astype(dtype) @ p["kernel"].astype(dtype)).astype(jnp.float32)
        y = y * p["kernel_scale"] + p["bias"].astype(jnp.float32)
        return y.astype(dtype)
    return (x.astype(dtype) @ p["kernel"].astype(dtype)
            + p["bias"].astype(dtype))


def _mlp(module, bp: dict, h2: jax.Array, dtype) -> jax.Array:
    """The block's MLP half over normalized activations h2 (B, S, D).

    MoE blocks re-apply the REAL MoEMLP flax module against the block's
    own params (same construction as TransformerBlock's, keep in sync —
    definitions.py), so routing math is never duplicated here.
    Per-segment routing matches training semantics exactly at prefill
    (same token group, same capacity arithmetic).  Decode steps route
    the step's BATCH as one group, so under capacity pressure routing
    can diverge from the full-sequence recompute in either direction
    (keep a token it would drop, or drop one it would keep), and a
    row's generations can depend on its co-batched rows — the capacity
    drop is a batch-level construct a stepwise decoder cannot reproduce.
    Tests pin prefill parity exactly and greedy parity in the drop-free
    regime (moe_group_size=1)."""
    if module.mlp_impl == "moe":
        from mmlspark_tpu.ops.moe import MoEMLP
        return MoEMLP(module.d_model, n_experts=module.n_experts,
                      mlp_ratio=module.mlp_ratio, dtype=dtype,
                      expert_axis=module.expert_axis,
                      router_k=module.moe_router_k,
                      group_size=module.moe_group_size).apply(
            {"params": bp["moe"]}, h2)
    return _dense(bp["mlp_down"], jax.nn.gelu(
        _dense(bp["mlp_up"], h2, dtype)), dtype)


_PREFILL_FLASH_MIN = 512  # prompt length from which prefill attention
# runs the pallas flash kernel instead of the masked dense matmul: long
# prompts would otherwise materialize an O(P^2) score tensor — exactly
# the blow-up the flash path exists to avoid.  Short prompts stay on the
# dense path, whose f32 softmax is bit-stable for the exact-parity tests.


def _block_with_cache(module, bp: dict, x: jax.Array, k_cache: jax.Array,
                      v_cache: jax.Array, pos, dtype):
    """One TransformerBlock over a token segment starting at `pos`,
    reading/writing the (B, max_len, H, Dh) caches.  Works for prefill
    (S = prompt length, pos = 0) and decode (S = 1, traced pos) alike."""
    n_heads = module.n_heads
    b, s, d = x.shape
    dh = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    qkv = _dense(bp["qkv"], h, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (b, s, n_heads, dh)
    q, k, v = (t.reshape(shape) for t in (q, k, v))
    k_cache = lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype),
                                       (0, pos, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype),
                                       (0, pos, 0, 0))
    if s >= _PREFILL_FLASH_MIN and isinstance(pos, int) and pos == 0:
        # long-prompt PREFILL ONLY (static pos 0: at decode, pos is a
        # tracer): attention against the cache is then exactly causal
        # self-attention over the segment, so the flash kernel
        # (O(block^2) memory, fwd-only) computes it without ever
        # materializing the (S, S) scores.  A long segment at pos > 0
        # would need the cached prefix too — it takes the dense
        # full-cache path below
        from mmlspark_tpu.ops.flash_attention import flash_attention
        o = flash_attention(q, k, v, causal=True)
    else:
        max_len = k_cache.shape[1]
        scores = jnp.einsum("bqhd,blhd->bhql", q.astype(jnp.float32),
                            k_cache.astype(jnp.float32)) * dh ** -0.5
        # global causal mask: query at pos+i sees cache slots 0..pos+i
        q_pos = pos + jnp.arange(s)
        visible = jnp.arange(max_len)[None, :] <= q_pos[:, None]  # (S, L)
        scores = jnp.where(visible[None, None], scores, NEG_INF)
        w = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhql,blhd->bqhd", w, v_cache.astype(jnp.float32))
    x = x + _dense(bp["proj"], o.reshape(b, s, d).astype(dtype), dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    return x + _mlp(module, bp, h2, dtype), k_cache, v_cache


def _forward_with_cache(params: dict, tokens: jax.Array, caches: list,
                        pos, module):
    """Logits (B, S, V) for a token segment at `pos`, updating the caches."""
    dtype = module.dtype
    s = tokens.shape[1]
    positions = pos + jnp.arange(s)
    emb = (params["tok_embed"]["embedding"][tokens]
           + params["pos_embed"]["embedding"][positions][None])
    x = emb.astype(dtype)
    new_caches = []
    for i in range(module.n_layers):
        x, kc, vc = _block_with_cache(
            module, params[f"block{i}_w"], x, caches[i][0], caches[i][1],
            pos, dtype)
        new_caches.append((kc, vc))
    # same dtype discipline as TransformerLM: final norm + head run in the
    # model's compute dtype, logits emitted float32
    x = _ln(params["final_norm_w"], x, dtype)
    logits = _dense(params["lm_head"], x, dtype).astype(jnp.float32)
    return logits, new_caches


def _seq_prefill_block(module, bp: dict, x: jax.Array, dtype,
                       seq_axis: str):
    """One TransformerBlock of the DISTRIBUTED blockwise prefill.  Runs
    inside the seq shard_map region with `x` the LOCAL token slab
    (B, P/n, D): attention is `ring_attention` — KV blocks rotate around
    the `seq` axis by ppermute while each chip keeps only its slab's
    queries resident — so prefill FLOPs, activation memory, and the
    O(P^2) score working set all scale ~1/n per chip.  Returns the
    residual stream plus this slab's K and V: the local shard of the
    layer's seq-partitioned KV cache, written exactly once with no
    gather."""
    from mmlspark_tpu.ops.attention import ring_attention
    n_heads = module.n_heads
    b, s, d = x.shape
    dh = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    qkv = _dense(bp["qkv"], h, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (b, s, n_heads, dh)
    q, k, v = (t.reshape(shape) for t in (q, k, v))
    # ring_attention derives each block's global query positions from
    # axis_index(seq_axis) internally, so causal masking is globally
    # correct over the rotating KV blocks; output is f32 (online softmax)
    o = ring_attention(q, k, v, seq_axis, causal=True)
    x = x + _dense(bp["proj"], o.reshape(b, s, d).astype(dtype), dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    return x + _mlp(module, bp, h2, dtype), k, v


def _check_generatable(module) -> None:
    if type(module).__name__ != "TransformerLM":
        raise ValueError(
            f"generate() decodes TransformerLM models, got "
            f"{type(module).__name__}")
    # any attention EXECUTION strategy trains the same weights; decode
    # always attends q against the cache, so attn_impl needs no check.
    # MoE blocks decode too: _mlp re-applies the real MoEMLP module.


def filter_logits(logits: jax.Array, top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jax.Array:
    """Mask (B, V) logits to the top-k entries and/or the top-p nucleus.

    top_k keeps the k highest-logit tokens per row; top_p keeps the
    smallest prefix of the probability-sorted vocabulary whose cumulative
    probability reaches p (the first token always survives, so the
    distribution never empties).  Everything else becomes NEG_INF —
    static-shape, sort-based, jit-friendly."""
    out = logits.astype(jnp.float32)
    if top_k is not None and top_k < out.shape[-1]:
        kth = jax.lax.top_k(out, top_k)[0][..., -1:]
        out = jnp.where(out >= kth, out, NEG_INF)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(out, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # a token is kept while the mass BEFORE it is < p (so the first
        # token is always kept); find the smallest kept logit
        keep = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        out = jnp.where(out >= cutoff, out, NEG_INF)
    return out


def _validate_decode_args(module, prompt_len: int,
                          max_new_tokens: int) -> None:
    """Shared budget checks for both decode entry points (sampler + beam)."""
    _check_generatable(module)
    if prompt_len < 1:
        raise ValueError("prompt_len must be >= 1")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if prompt_len + max_new_tokens > module.max_len:
        raise ValueError(
            f"prompt_len ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds the model's max_len ({module.max_len})")


def _prefill(params, prompts, module, prompt_len: int):
    """Allocate zero caches, run the prompt forward, return (last-position
    logits, caches).  Raises at trace time on a prompt-length mismatch — a
    compiled fn reused at the wrong length would decode against
    never-written cache slots."""
    if prompts.shape[1] != prompt_len:
        raise ValueError(
            f"prompts have length {prompts.shape[1]} but this compiled "
            f"decode program was built for prompt_len={prompt_len}")
    b = prompts.shape[0]
    dh = module.d_model // module.n_heads
    caches = [(_hint_kv(jnp.zeros((b, module.max_len, module.n_heads, dh),
                                  module.dtype)),
               _hint_kv(jnp.zeros((b, module.max_len, module.n_heads, dh),
                                  module.dtype)))
              for _ in range(module.n_layers)]
    logits, caches = _forward_with_cache(params, prompts, caches, 0, module)
    return logits[:, -1], caches


def make_generate_fn(module, prompt_len: int, max_new_tokens: int,
                     temperature: float = 0.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None):
    """A jitted `(variables, prompts (B, P) int32, rng_key) -> (B, P+N)`
    generation program for one (prompt_len, max_new_tokens) shape class.

    Compiled once per shape class; TextGenerator caches these.  The prompt
    must fit the model: prompt_len + max_new_tokens <= max_len (position
    embeddings are the budget).  Sampling is greedy at temperature 0;
    otherwise temperature-scaled categorical over the top_k / top_p
    (nucleus) filtered distribution (`filter_logits`)."""
    _validate_decode_args(module, prompt_len, max_new_tokens)
    if top_k is not None and top_k < 1:
        raise ValueError("top_k must be >= 1")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError("top_p must be in (0, 1]")
    greedy = temperature <= 0.0

    def sample(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # temperature first, then filter: the nucleus mass is measured on
        # the distribution actually sampled (the standard ordering)
        filtered = filter_logits(
            logits.astype(jnp.float32) / temperature, top_k, top_p)
        return jax.random.categorical(key, filtered,
                                      axis=-1).astype(jnp.int32)

    @jax.jit
    def generate_fn(variables, prompts, key):
        params = variables["params"]
        last_logits, caches = _prefill(params, prompts, module, prompt_len)
        key, sub = jax.random.split(key)
        tok = sample(last_logits, sub)

        def step(carry, step_key):
            tok, pos, caches = carry
            logits, caches = _forward_with_cache(
                params, tok[:, None], caches, pos, module)
            nxt = sample(logits[:, 0], step_key)
            return (nxt, pos + 1, caches), tok

        if max_new_tokens > 1:
            (tok, _, _), toks = lax.scan(
                step, (tok, jnp.asarray(prompt_len, jnp.int32), caches),
                jax.random.split(key, max_new_tokens - 1))
            generated = jnp.concatenate(
                [toks.transpose(1, 0), tok[:, None]], axis=1)
        else:
            generated = tok[:, None]
        return jnp.concatenate([prompts, generated], axis=1)

    return generate_fn


def generate(module, variables, prompts, max_new_tokens: int,
             temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             rng: Optional[jax.Array] = None) -> np.ndarray:
    """One-shot convenience wrapper around `make_generate_fn` (which is
    the jit-once API for repeated calls)."""
    prompts = jnp.asarray(prompts, jnp.int32)
    fn = make_generate_fn(module, prompts.shape[1], max_new_tokens,
                          temperature, top_k=top_k, top_p=top_p)
    key = rng if rng is not None else jax.random.key(0)
    return np.asarray(fn(variables, prompts, key))


def make_beam_search_fn(module, prompt_len: int, max_new_tokens: int,
                        beam_width: int):
    """A jitted `(variables, prompts (B, P) int32) -> (tokens, scores)`
    beam-search program: tokens (B, W, P+N) ordered best-first per row,
    scores (B, W) the summed token log-probabilities of each beam's
    generated region.

    Deterministic length-N beams (token-id models here carry no reserved
    EOS, so no early stopping and no length penalty — all candidates have
    equal length and rank directly by total log-probability).  Mechanics:
    the prompt prefills ONCE per row, caches are then expanded to B*W
    rows, and each scan step scores all beams' vocab expansions, keeps
    the top W of W*V per row, and RE-INDEXES both the cache rows and the
    token history to the surviving beams' ancestors — static shapes
    throughout, so the whole search is one compiled program."""
    _validate_decode_args(module, prompt_len, max_new_tokens)
    if beam_width < 1:
        raise ValueError("beam_width must be >= 1")
    if beam_width > module.vocab_size:
        raise ValueError(
            f"beam_width ({beam_width}) cannot exceed the vocabulary "
            f"({module.vocab_size}): the first expansion keeps beam_width "
            "distinct tokens")
    w = beam_width

    @jax.jit
    def beam_fn(variables, prompts):
        params = variables["params"]
        b = prompts.shape[0]
        v = module.vocab_size
        last_logits, caches = _prefill(params, prompts, module, prompt_len)
        logprobs = jax.nn.log_softmax(last_logits, axis=-1)     # (B, V)
        scores, tok = lax.top_k(logprobs, w)                    # (B, W)
        tok = tok.astype(jnp.int32)
        # every beam of a row shares the prompt's cache: expand B -> B*W
        caches = [(jnp.repeat(kc, w, axis=0), jnp.repeat(vc, w, axis=0))
                  for kc, vc in caches]
        history = jnp.zeros((b, w, max_new_tokens), jnp.int32)
        history = history.at[:, :, 0].set(tok)
        row_base = jnp.arange(b)[:, None] * w                   # (B, 1)

        def step(carry, t):
            tok, scores, history, caches = carry
            logits, caches = _forward_with_cache(
                params, tok.reshape(b * w, 1), caches,
                prompt_len + t, module)
            logprobs = jax.nn.log_softmax(
                logits[:, 0], axis=-1).reshape(b, w, v)
            total = scores[:, :, None] + logprobs               # (B, W, V)
            scores, flat_idx = lax.top_k(total.reshape(b, w * v), w)
            beam_idx = flat_idx // v                            # ancestor
            tok = (flat_idx % v).astype(jnp.int32)
            take = (row_base + beam_idx).reshape(-1)            # (B*W,)
            caches = [(kc[take], vc[take]) for kc, vc in caches]
            history = jnp.take_along_axis(
                history, beam_idx[:, :, None], axis=1)
            history = history.at[:, :, t + 1].set(tok)
            return (tok, scores, history, caches), None

        if max_new_tokens > 1:
            (tok, scores, history, caches), _ = lax.scan(
                step, (tok, scores, history, caches),
                jnp.arange(max_new_tokens - 1))
        tokens = jnp.concatenate(
            [jnp.broadcast_to(prompts[:, None], (b, w, prompt_len)),
             history], axis=2)
        return tokens, scores

    return beam_fn


def beam_search(module, variables, prompts, max_new_tokens: int,
                beam_width: int = 4):
    """One-shot convenience wrapper around `make_beam_search_fn`.
    Returns (tokens (B, W, P+N) best-first, scores (B, W))."""
    prompts = jnp.asarray(prompts, jnp.int32)
    fn = make_beam_search_fn(module, prompts.shape[1], max_new_tokens,
                             beam_width)
    tokens, scores = fn(variables, prompts)
    return np.asarray(tokens), np.asarray(scores)


# ---------------------------------------------------------------------------
# The decode engine: bucketed prefill + cache-windowed segments + early exit
# ---------------------------------------------------------------------------

DEFAULT_CACHE_CHUNK = 128  # cache-window growth granularity (slots): the
# compiled decode step attends over the cache prefix rounded up to this,
# so steady-step bandwidth tracks occupancy in chunk-sized increments
DEFAULT_MIN_BUCKET = 8     # smallest prompt bucket: below this, shape-class
# consolidation saves more than the pad compute costs


def _round_up(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def bucket_length(n: int, max_len: int, max_new_tokens: int,
                  min_bucket: int = DEFAULT_MIN_BUCKET) -> int:
    """The prompt bucket for a true length `n`: next power of two, floored
    at `min_bucket` and capped at `max_len - max_new_tokens` (the cap keeps
    every bucket decodable to the full generation budget; position
    embeddings are indexed by TRUE per-row positions, so the cap — not the
    bucket's pad tail — is what the position table bounds)."""
    cap = max_len - max_new_tokens
    if n < 1:
        raise ValueError("prompt length must be >= 1")
    if n > cap:
        raise ValueError(
            f"prompt length ({n}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds the model's max_len ({max_len})")
    return min(max(1 << (n - 1).bit_length(), min_bucket), cap)


def decode_segments(bucket: int, max_new_tokens: int,
                    chunk: int) -> list:
    """The static segment plan for a windowed decode: a list of
    (start_step, seg_len, window) covering scan steps 0..max_new_tokens-2
    (step s writes cache slot bucket+s; the first generated token comes
    from prefill).  `window` is the chunk-rounded cover of the segment's
    highest written slot, and segments are additionally capped at `chunk`
    steps so the early-exit host check runs at least once per chunk."""
    if chunk < 1:
        raise ValueError("chunk must be >= 1")
    segs = []
    s = 0
    while s <= max_new_tokens - 2:
        w = _round_up(bucket + s + 1, chunk)
        last = min(w - bucket - 1, s + chunk - 1, max_new_tokens - 2)
        segs.append((s, last - s + 1, w))
        s = last + 1
    return segs


def _make_sampler(temperature: float, top_k, top_p):
    """A `(logits (B, V), row_keys (B,), step) -> tokens (B,)` sampler with
    per-row keys: each row's stream is `fold_in(row_key, step)`, so a
    row's draws depend only on (its key, the step index) — never on which
    rows share its batch or how groups were formed."""
    if temperature <= 0.0:
        def sample(logits, row_keys, step):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        def sample(logits, row_keys, step):
            filtered = filter_logits(
                logits.astype(jnp.float32) / temperature, top_k, top_p)
            keys = jax.vmap(lambda k: jax.random.fold_in(k, step))(row_keys)
            return jax.vmap(jax.random.categorical)(
                keys, filtered).astype(jnp.int32)
    return sample


def _make_row_sampler(temperature: float, top_k, top_p):
    """The per-row-STEP form of `_make_sampler`: `steps` is a (B,) vector,
    so rows at different decode offsets (the serving engine's continuous
    batch) draw from exactly the stream positions the uniform-step batch
    path would have given them — fold_in(row_key, step) per row."""
    if temperature <= 0.0:
        def sample(logits, row_keys, steps):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        def sample(logits, row_keys, steps):
            filtered = filter_logits(
                logits.astype(jnp.float32) / temperature, top_k, top_p)
            keys = jax.vmap(jax.random.fold_in)(row_keys, steps)
            return jax.vmap(jax.random.categorical)(
                keys, filtered).astype(jnp.int32)
    return sample


def _make_stop_check(stop_tokens: tuple):
    if not stop_tokens:
        return lambda tok: jnp.zeros(tok.shape, bool)
    stops = jnp.asarray(list(stop_tokens), jnp.int32)
    return lambda tok: (tok[:, None] == stops[None, :]).any(axis=-1)


def _quantize_cache(kc: jax.Array, vc: jax.Array) -> tuple:
    """Convert one layer's model-dtype caches to the int8 layout:
    (k int8, k_scale f32 (B, W, H), v int8, v_scale)."""
    from mmlspark_tpu.quant.quantize import quantize_kv
    kq, ks = quantize_kv(kc)
    vq, vs = quantize_kv(vc)
    return kq, ks, vq, vs


def _sq_attention(fused: bool):
    """The decode step's cache read.  `fused=True` routes through the
    Pallas single-query kernel (ops/decode_attention.py) — which itself
    degrades to the XLA reference off-TPU or on shapes it can't tile, so
    tier-1 CPU runs exercise the fallback on the product path.  The
    engine only requests it single-device: `pallas_call` carries no SPMD
    partitioning rule, so under a mesh the decode step keeps the einsum
    composition GSPMD can shard."""
    if fused:
        from mmlspark_tpu.ops.decode_attention import (
            fused_single_query_attention)
        return fused_single_query_attention
    from mmlspark_tpu.ops.attention import single_query_attention
    return single_query_attention


def _decode_block(module, bp: dict, x: jax.Array, cache: tuple,
                  slot, visible, dtype, cache_kind: str,
                  fused: bool = False):
    """One TransformerBlock for a single decode token: write K/V at cache
    `slot` (shared across rows — decode slots sit after the bucket's pad
    tail), attend under the per-row `visible` mask (true-prompt slots plus
    decode slots written so far), MLP as in `_block_with_cache`.

    `cache` is (k, v) for a model-dtype cache or (k_q, k_scale, v_q,
    v_scale) for an int8 one (cache_kind 'int8'): the new token's K/V are
    quantized per-head ON WRITE and the attention read dequantizes inside
    the cache attention (`_sq_attention`: the fused Pallas kernel on a
    single TPU device, `single_query_attention` otherwise) — the steady
    step streams 1 byte per cached element instead of the model dtype's
    2-4."""
    single_query_attention = _sq_attention(fused)
    n_heads = module.n_heads
    b, s, d = x.shape
    dh = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    qkv = _dense(bp["qkv"], h, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (b, 1, n_heads, dh)
    q, k, v = (t.reshape(shape) for t in (q, k, v))
    if cache_kind == "int8":
        from mmlspark_tpu.quant.quantize import quantize_kv
        kq, ks, vq, vs = cache
        k8, k8s = quantize_kv(k)
        v8, v8s = quantize_kv(v)
        kq = lax.dynamic_update_slice(kq, k8, (0, slot, 0, 0))
        ks = lax.dynamic_update_slice(ks, k8s, (0, slot, 0))
        vq = lax.dynamic_update_slice(vq, v8, (0, slot, 0, 0))
        vs = lax.dynamic_update_slice(vs, v8s, (0, slot, 0))
        o = single_query_attention(q[:, 0], kq, vq, visible,
                                   k_scale=ks, v_scale=vs)
        cache = (kq, ks, vq, vs)
    else:
        k_cache, v_cache = cache
        k_cache = lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, slot, 0, 0))
        v_cache = lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, slot, 0, 0))
        o = single_query_attention(q[:, 0], k_cache, v_cache, visible)
        cache = (k_cache, v_cache)
    x = x + _dense(bp["proj"], o.reshape(b, 1, d).astype(dtype), dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    return x + _mlp(module, bp, h2, dtype), cache


def _decode_step(params: dict, tok: jax.Array, pos: jax.Array, slot,
                 caches: list, visible, module, cache_kind: str = "model",
                 fused: bool = False):
    """Logits (B, V) for one decode token per row: per-row positions `pos`
    (true prompt length + step — NOT the shared cache slot), shared write
    `slot`, per-row attention visibility."""
    dtype = module.dtype
    emb = (params["tok_embed"]["embedding"][tok]
           + params["pos_embed"]["embedding"][pos])
    x = emb[:, None].astype(dtype)
    new_caches = []
    for i in range(module.n_layers):
        x, cache = _decode_block(module, params[f"block{i}_w"], x,
                                 caches[i], slot, visible, dtype,
                                 cache_kind, fused)
        new_caches.append(cache)
    x = _ln(params["final_norm_w"], x, dtype)
    logits = _dense(params["lm_head"], x, dtype).astype(jnp.float32)
    return logits[:, 0], new_caches


def _seq_decode_block(module, bp: dict, x: jax.Array, cache: tuple,
                      slot, lo, visible, dtype, cache_kind: str,
                      seq_axis: str):
    """`_decode_block` for a SEQ-SHARDED cache, running inside the seq
    shard_map region.  Each chip holds a contiguous window slab of `w_l`
    slots starting at its `lo = axis_index(seq) * w_l`; the new token's
    K/V land on exactly the one chip that owns global `slot` (`owns` is
    a traced scalar — every chip computes the candidate write, the
    non-owners discard it via `jnp.where`, so no cross-chip writes ever
    happen).  Attention reads become per-chip softmax STATS
    (`single_query_attention_stats`: f32 running (acc, m, l) against the
    local slab under the local slice of `visible`) merged across `seq`
    by `merge_attention_stats` — one pmax + two psums per layer instead
    of gathering the window.  int8 dequant scales compose unchanged:
    dequantization happens inside the local stats pass, before the
    merge."""
    from mmlspark_tpu.ops.attention import (merge_attention_stats,
                                            single_query_attention_stats)
    n_heads = module.n_heads
    b, s, d = x.shape
    dh = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    qkv = _dense(bp["qkv"], h, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (b, 1, n_heads, dh)
    q, k, v = (t.reshape(shape) for t in (q, k, v))
    w_l = cache[0].shape[1]
    owns = (slot >= lo) & (slot < lo + w_l)
    local_slot = jnp.clip(slot - lo, 0, w_l - 1)
    if cache_kind == "int8":
        from mmlspark_tpu.quant.quantize import quantize_kv
        kq, ks, vq, vs = cache
        k8, k8s = quantize_kv(k)
        v8, v8s = quantize_kv(v)
        kq = jnp.where(owns, lax.dynamic_update_slice(
            kq, k8, (0, local_slot, 0, 0)), kq)
        ks = jnp.where(owns, lax.dynamic_update_slice(
            ks, k8s, (0, local_slot, 0)), ks)
        vq = jnp.where(owns, lax.dynamic_update_slice(
            vq, v8, (0, local_slot, 0, 0)), vq)
        vs = jnp.where(owns, lax.dynamic_update_slice(
            vs, v8s, (0, local_slot, 0)), vs)
        acc, m, l = single_query_attention_stats(q[:, 0], kq, vq, visible,
                                                 k_scale=ks, v_scale=vs)
        cache = (kq, ks, vq, vs)
    else:
        k_cache, v_cache = cache
        k_cache = jnp.where(owns, lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, local_slot, 0, 0)),
            k_cache)
        v_cache = jnp.where(owns, lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, local_slot, 0, 0)),
            v_cache)
        acc, m, l = single_query_attention_stats(q[:, 0], k_cache, v_cache,
                                                 visible)
        cache = (k_cache, v_cache)
    o = merge_attention_stats(acc, m, l, axis_name=seq_axis)
    x = x + _dense(bp["proj"], o.reshape(b, 1, d).astype(dtype), dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    return x + _mlp(module, bp, h2, dtype), cache


def _seq_decode_step(params: dict, tok: jax.Array, pos: jax.Array, slot,
                     lo, caches: list, visible, module,
                     cache_kind: str, seq_axis: str):
    """`_decode_step` inside the seq shard_map region: same per-row
    positions / shared global write `slot`, but `visible` covers only
    the local window slab and each block merges softmax stats across
    `seq`.  The non-attention compute (embeddings, MLPs, head) is
    replicated per seq shard — deterministic-identical on every chip, so
    the logits really are replicated over `seq` as the out_specs
    claim."""
    dtype = module.dtype
    emb = (params["tok_embed"]["embedding"][tok]
           + params["pos_embed"]["embedding"][pos])
    x = emb[:, None].astype(dtype)
    new_caches = []
    for i in range(module.n_layers):
        x, cache = _seq_decode_block(module, params[f"block{i}_w"], x,
                                     caches[i], slot, lo, visible, dtype,
                                     cache_kind, seq_axis)
        new_caches.append(cache)
    x = _ln(params["final_norm_w"], x, dtype)
    logits = _dense(params["lm_head"], x, dtype).astype(jnp.float32)
    return logits[:, 0], new_caches


def _row_write(cache: jax.Array, update: jax.Array,
               slots: jax.Array) -> jax.Array:
    """Write a contiguous block of new entries per row at a PER-ROW
    start slot: vmap of the single-row dynamic_update_slice over the
    batch axis.  `cache` (B, W, ...), `update` (B, S, ...) — S is 1 for
    decode steps, the verify segment length for speculative decoding —
    `slots` (B,) int32 start positions.  The serving
    engine's continuous batch needs this — joined rows sit at different
    decode offsets, so the uniform shared-slot write of `_decode_block`
    no longer applies.  dynamic_update_slice clamps starts, so a frozen
    row whose slot has run past the window writes harmlessly into its own
    last slot (its `done` mask keeps the output frozen regardless)."""
    zeros = (0,) * (cache.ndim - 2)
    return jax.vmap(
        lambda c, u, s: lax.dynamic_update_slice(c, u, (s,) + zeros)
    )(cache, update, slots)


def _decode_block_rows(module, bp: dict, x: jax.Array, cache: tuple,
                       slots, visible, dtype, cache_kind: str,
                       fused: bool = False):
    """`_decode_block` with PER-ROW write slots (serving engine): row r
    writes its K/V at `slots[r]` instead of one shared slot.  Math and
    cache layouts are identical otherwise — same quantize-on-write int8
    discipline, same cache-attention read (`_sq_attention`)."""
    single_query_attention = _sq_attention(fused)
    n_heads = module.n_heads
    b, s, d = x.shape
    dh = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    qkv = _dense(bp["qkv"], h, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (b, 1, n_heads, dh)
    q, k, v = (t.reshape(shape) for t in (q, k, v))
    if cache_kind == "int8":
        from mmlspark_tpu.quant.quantize import quantize_kv
        kq, ks, vq, vs = cache
        k8, k8s = quantize_kv(k)
        v8, v8s = quantize_kv(v)
        kq = _row_write(kq, k8, slots)
        ks = _row_write(ks, k8s, slots)
        vq = _row_write(vq, v8, slots)
        vs = _row_write(vs, v8s, slots)
        o = single_query_attention(q[:, 0], kq, vq, visible,
                                   k_scale=ks, v_scale=vs)
        cache = (kq, ks, vq, vs)
    else:
        k_cache, v_cache = cache
        k_cache = _row_write(k_cache, k.astype(k_cache.dtype), slots)
        v_cache = _row_write(v_cache, v.astype(v_cache.dtype), slots)
        o = single_query_attention(q[:, 0], k_cache, v_cache, visible)
        cache = (k_cache, v_cache)
    x = x + _dense(bp["proj"], o.reshape(b, 1, d).astype(dtype), dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    return x + _mlp(module, bp, h2, dtype), cache


def _decode_step_rows(params: dict, tok: jax.Array, pos: jax.Array, slots,
                      caches: list, visible, module,
                      cache_kind: str = "model", fused: bool = False):
    """`_decode_step` with per-row write `slots` (B,) — the continuous-
    batching decode step.  `pos` stays per-row true positions; callers
    clamp it below max_len for frozen rows (their output is masked by
    `done` anyway, but the position gather must stay in range)."""
    dtype = module.dtype
    emb = (params["tok_embed"]["embedding"][tok]
           + params["pos_embed"]["embedding"][pos])
    x = emb[:, None].astype(dtype)
    new_caches = []
    for i in range(module.n_layers):
        x, cache = _decode_block_rows(module, params[f"block{i}_w"], x,
                                      caches[i], slots, visible, dtype,
                                      cache_kind, fused)
        new_caches.append(cache)
    x = _ln(params["final_norm_w"], x, dtype)
    logits = _dense(params["lm_head"], x, dtype).astype(jnp.float32)
    return logits[:, 0], new_caches


def _verify_block_rows(module, bp: dict, x: jax.Array, cache: tuple,
                       slots0, visible, dtype, cache_kind: str):
    """One TransformerBlock over a row's CONTIGUOUS S-token verify
    segment (speculative decoding): row r writes S new K/V entries at
    slots0[r]..slots0[r]+S-1 in one per-row block write (`_row_write`
    takes any update length), then attends all S queries against the
    cache window under per-QUERY visibility
    (ops/attention.segment_cache_attention).  Same quantize-on-write
    int8 discipline as `_decode_block_rows`; at S = 1 the attention math
    is elementwise-identical to the single-query step — the property the
    speculative path's greedy byte-exactness rests on."""
    from mmlspark_tpu.ops.attention import segment_cache_attention
    n_heads = module.n_heads
    b, s, d = x.shape
    dh = d // n_heads
    h = _ln(bp["LayerNorm_0"], x, dtype)
    qkv = _dense(bp["qkv"], h, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (b, s, n_heads, dh)
    q, k, v = (t.reshape(shape) for t in (q, k, v))
    if cache_kind == "int8":
        from mmlspark_tpu.quant.quantize import quantize_kv
        kq, ks, vq, vs = cache
        k8, k8s = quantize_kv(k)
        v8, v8s = quantize_kv(v)
        kq = _row_write(kq, k8, slots0)
        ks = _row_write(ks, k8s, slots0)
        vq = _row_write(vq, v8, slots0)
        vs = _row_write(vs, v8s, slots0)
        o = segment_cache_attention(q, kq, vq, visible,
                                    k_scale=ks, v_scale=vs)
        cache = (kq, ks, vq, vs)
    else:
        k_cache, v_cache = cache
        k_cache = _row_write(k_cache, k.astype(k_cache.dtype), slots0)
        v_cache = _row_write(v_cache, v.astype(v_cache.dtype), slots0)
        o = segment_cache_attention(q, k_cache, v_cache, visible)
        cache = (k_cache, v_cache)
    x = x + _dense(bp["proj"], o.reshape(b, s, d).astype(dtype), dtype)
    h2 = _ln(bp["LayerNorm_1"], x, dtype)
    return x + _mlp(module, bp, h2, dtype), cache


def _verify_step_rows(params: dict, toks: jax.Array, pos0: jax.Array,
                      slots0, caches: list, visible, module,
                      cache_kind: str = "model"):
    """Logits (B, S, V) for per-row contiguous verify segments — the
    speculative-decoding target forward: ONE program scores every drafted
    position.  Row r's S tokens sit at positions pos0[r]..pos0[r]+S-1
    (clamped to the position table) and write cache slots
    slots0[r]..slots0[r]+S-1; `visible` is per-query (B, S, W)."""
    dtype = module.dtype
    s = toks.shape[1]
    positions = pos0[:, None] + jnp.arange(s)[None, :]
    positions = jnp.minimum(positions, module.max_len - 1)
    emb = (params["tok_embed"]["embedding"][toks]
           + params["pos_embed"]["embedding"][positions])
    x = emb.astype(dtype)
    new_caches = []
    for i in range(module.n_layers):
        x, cache = _verify_block_rows(module, params[f"block{i}_w"], x,
                                      caches[i], slots0, visible, dtype,
                                      cache_kind)
        new_caches.append(cache)
    x = _ln(params["final_norm_w"], x, dtype)
    logits = _dense(params["lm_head"], x, dtype).astype(jnp.float32)
    return logits, new_caches


def _grow_cache(cache: jax.Array, window: int) -> jax.Array:
    """Zero-extend a cache prefix to `window` slots (static shapes).
    Rank-agnostic over the trailing axes: the (B, W, H, D) payloads and
    the (B, W, H) int8-cache scale arrays grow the same way."""
    w_in = cache.shape[1]
    if w_in == window:
        return cache
    pad = [(0, 0), (0, window - w_in)] + [(0, 0)] * (cache.ndim - 2)
    return jnp.pad(cache, pad)


@jax.jit
def _merge_cache_rows_jit(dst_caches, src_caches, di, si):
    window = max(dst_caches[0][0].shape[1], src_caches[0][0].shape[1])
    merged = []
    for dst_layer, src_layer in zip(dst_caches, src_caches):
        merged.append(tuple(
            _hint_kv(_grow_cache(d, window).at[di].set(
                _grow_cache(s, window)[si]))
            for d, s in zip(dst_layer, src_layer)))
    return merged


_PAGE_LAYOUT = None  # lazy structs for the KV-page wire layout


def _page_structs():
    global _PAGE_LAYOUT
    if _PAGE_LAYOUT is None:
        import struct
        # page header (n_layers, n_tensors); per-tensor header
        # (dtype-name length, ndim); dims and byte lengths as >I
        _PAGE_LAYOUT = (struct.Struct(">HH"), struct.Struct(">BB"),
                        struct.Struct(">I"))
    return _PAGE_LAYOUT


def _wire_dtype(name: str) -> np.dtype:
    """Resolve a serialized dtype name, including the ml_dtypes extras
    (bfloat16 is the default model dtype and has no native numpy name —
    np.save would silently degrade it to a void dtype)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except (AttributeError, TypeError) as e:
            raise ValueError(f"unknown page tensor dtype {name!r}") from e


def serialize_cache_row(caches, row: int, chunk: int) -> list:
    """Cut ONE row of a serve cache into chunk-granular window pages for
    the prefill->decode handoff: each page is a self-describing blob
    (dtype name + shape + raw bytes per layer-tensor window slice) that
    `deserialize_cache_row` reassembles without any side-channel layout
    info.  Works for both cache layouts — the 2-tuple model-dtype (k, v)
    and the 4-tuple int8 (kq, k_scale, vq, v_scale); int8 pages
    naturally shrink the wire bytes, which is the point of quantizing
    BEFORE shipping.  The explicit dtype name (not npy) is what keeps
    bfloat16 byte-exact across the wire.  Seq-sharded caches gather here
    IMPLICITLY: `np.asarray` on a sharded row pulls the full window to
    host — fine on single-process (fully-addressable) meshes, which is
    the only place this serializer runs."""
    import io
    page_hdr, tens_hdr, u32 = _page_structs()
    host = [[np.asarray(t[row]) for t in layer] for layer in caches]
    width = host[0][0].shape[0]
    pages = []
    for lo in range(0, width, max(1, int(chunk))):
        hi = min(width, lo + max(1, int(chunk)))
        bio = io.BytesIO()
        bio.write(page_hdr.pack(len(host), len(host[0])))
        for layer in host:
            for tensor in layer:
                part = np.ascontiguousarray(tensor[lo:hi])
                name = part.dtype.name.encode("ascii")
                bio.write(tens_hdr.pack(len(name), part.ndim))
                bio.write(name)
                for dim in part.shape:
                    bio.write(u32.pack(dim))
                raw = part.tobytes()
                bio.write(u32.pack(len(raw)))
                bio.write(raw)
        pages.append(bio.getvalue())
    return pages


def deserialize_cache_row(pages: list) -> list:
    """Reassemble `serialize_cache_row` pages (in chunk order) into a
    1-row cache ready for `DecodeEngine.merge_cache_rows` — window
    slices concatenate back on the window axis and gain the batch dim.
    Byte-exact: dtype and bits round-trip untouched."""
    import io
    if not pages:
        raise ValueError("cannot deserialize an empty page list")
    page_hdr, tens_hdr, u32 = _page_structs()

    def read(bio, n):
        data = bio.read(n)
        if len(data) != n:
            raise ValueError("short page: truncated tensor record")
        return data

    parts = None
    for blob in pages:
        bio = io.BytesIO(blob)
        n_layers, n_tensors = page_hdr.unpack(read(bio, page_hdr.size))
        if parts is None:
            parts = [[[] for _ in range(n_tensors)]
                     for _ in range(n_layers)]
        elif len(parts) != n_layers or len(parts[0]) != n_tensors:
            raise ValueError("page layout mismatch across pages")
        for li in range(n_layers):
            for ti in range(n_tensors):
                nlen, ndim = tens_hdr.unpack(read(bio, tens_hdr.size))
                dtype = _wire_dtype(read(bio, nlen).decode("ascii"))
                shape = tuple(u32.unpack(read(bio, u32.size))[0]
                              for _ in range(ndim))
                (nbytes,) = u32.unpack(read(bio, u32.size))
                arr = np.frombuffer(read(bio, nbytes), dtype=dtype)
                parts[li][ti].append(arr.reshape(shape))
    return [tuple(jnp.asarray(np.concatenate(tensors, axis=0))[None]
                  for tensors in layer)
            for layer in parts]


class DecodeEngine:
    """Bucketed, cache-windowed, early-exit generation for one sampling
    configuration (the module docstring has the design).

    Two jitted programs serve every bucket: `_prefill` (specialized per
    (batch, bucket) shape) and `_segment` (specialized per (batch,
    window-in, window, seg_len) — bucket and step offsets are traced
    scalars, so buckets whose windows coincide share compiled segments).
    `compiled_programs` counts the distinct shape classes built so far —
    the number the ragged-workload bench pins.

    `cache_dtype='int8'` stores the KV cache quantized (per-head symmetric
    int8, quantize-on-write; dequant inside the attention read,
    ops/attention.py) — the steady decode step streams 1 byte per cached
    element instead of the model dtype's 2-4, which is the win on a
    bandwidth-bound step.  Quantizing the cache changes numerics (~1/254
    relative per element), so near-tie greedy choices can flip; top-1
    agreement with the model-dtype cache is test-pinned on a fixed-seed
    model, and bench reports the agreement next to the step-time speedup.

    Greedy token parity with `make_generate_fn`'s full-cache per-length
    decoder is exact at float32 (test-pinned): pad slots carry exactly
    zero attention weight and positions are per-row true positions, so
    bucketing and windowing are pure layout.  For bfloat16 bundles the
    same caveat as the module docstring's recompute-parity note applies:
    padded-shape matmuls can tile differently at bf16 resolution, so
    near-tie greedy choices (top-2 gap of one bf16 ulp) may legitimately
    resolve differently between bucket layouts.
    """

    def __init__(self, module, max_new_tokens: int, *,
                 temperature: float = 0.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 stop_tokens: tuple = (),
                 chunk: int = DEFAULT_CACHE_CHUNK,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 cache_dtype: str = "model", mesh=None,
                 min_new_tokens: int = 1,
                 prefill_chunk: Optional[int] = None,
                 draft_module=None, spec_tokens: int = 0):
        _check_generatable(module)
        if cache_dtype not in ("model", "int8"):
            raise ValueError(
                f"unknown cache_dtype '{cache_dtype}' (model | int8)")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if max_new_tokens >= module.max_len:
            raise ValueError(
                f"max_new_tokens ({max_new_tokens}) leaves no room for a "
                f"prompt within max_len ({module.max_len})")
        if top_k is not None and top_k < 1:
            raise ValueError("top_k must be >= 1")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if chunk < 1:
            raise ValueError("chunk must be >= 1")
        if not 1 <= min_new_tokens <= max_new_tokens:
            raise ValueError(
                f"min_new_tokens ({min_new_tokens}) must be in "
                f"1..max_new_tokens ({max_new_tokens})")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                "prefill_chunk must be >= 1 (None = whole-prompt)")
        if spec_tokens < 0:
            raise ValueError("spec_tokens must be >= 0")
        if spec_tokens and draft_module is None:
            raise ValueError(
                "spec_tokens > 0 needs a draft_module (zoo/speculative.py "
                "builds one from a target bundle)")
        if draft_module is not None:
            if spec_tokens < 1:
                raise ValueError("draft_module set but spec_tokens is 0")
            _check_generatable(draft_module)
            if draft_module.vocab_size != module.vocab_size:
                raise ValueError(
                    f"draft vocab ({draft_module.vocab_size}) != target "
                    f"vocab ({module.vocab_size}): speculative acceptance "
                    "compares distributions over one vocabulary")
            if draft_module.max_len < module.max_len:
                raise ValueError(
                    f"draft max_len ({draft_module.max_len}) < target "
                    f"max_len ({module.max_len}): the draft must reach "
                    "every position the target decodes")
            if module.mlp_impl == "moe" or draft_module.mlp_impl == "moe":
                raise ValueError(
                    "speculative decoding does not support MoE models: "
                    "the multi-token verify forward routes a different "
                    "capacity group than step-by-step decode, so "
                    "greedy-exactness cannot hold (see _mlp)")
        stop_tokens = tuple(int(t) for t in stop_tokens or ())
        for t in stop_tokens:
            if not 0 <= t < module.vocab_size:
                raise ValueError(
                    f"stop token {t} outside the vocabulary "
                    f"(0..{module.vocab_size - 1})")
        seq_shards = (int(mesh.shape.get(SEQ_AXIS, 1))
                      if mesh is not None else 1)
        if seq_shards > 1:
            # the seq-sharded engine path: long-context decode with the
            # KV window partitioned over 'seq'.  Its refusals bound the
            # composition space — everything below is a real algorithmic
            # conflict, not a not-yet
            if int(mesh.shape.get(MODEL_AXIS, 1)) > 1:
                raise ValueError(
                    "seq-sharded decode (mesh seq>1) does not compose "
                    "with model>1: the seq path keeps heads unsharded "
                    "(SEQ_KV_CACHE_SPEC) so the stats merge is the only "
                    "cross-chip attention collective")
            if module.mlp_impl == "moe":
                raise ValueError(
                    "seq-sharded decode does not support MoE models: "
                    "per-shard expert routing would diverge from the "
                    "global capacity groups (see _mlp)")
            if draft_module is not None:
                raise ValueError(
                    "seq-sharded decode does not compose with "
                    "speculative decoding: the multi-token verify "
                    "forward has no seq-sharded cache path")
            if prefill_chunk is not None:
                raise ValueError(
                    "seq-sharded decode does not compose with chunked "
                    "prefill: distributed blockwise (ring) prefill "
                    "already splits the prompt over chips")
            if chunk % seq_shards:
                raise ValueError(
                    f"cache chunk ({chunk}) must divide by the mesh seq "
                    f"axis ({seq_shards}) so every window width shards "
                    "evenly")
            if min_bucket % seq_shards:
                raise ValueError(
                    f"min_bucket ({min_bucket}) must divide by the mesh "
                    f"seq axis ({seq_shards}) so every prompt bucket "
                    "shards evenly")
        self.module = module
        self.max_new_tokens = max_new_tokens
        self.stop_tokens = stop_tokens
        self.chunk = chunk
        self.min_bucket = min_bucket
        self.cache_dtype = cache_dtype
        self.min_new_tokens = min_new_tokens
        self.prefill_chunk = prefill_chunk
        self.draft_module = draft_module
        self.spec_tokens = spec_tokens
        # the mesh the KV hints target: every compiled program (prefill,
        # segments, merge) traces under use_mesh(mesh), so at mp >= 2 the
        # cache keeps heads on 'model' end to end; None = single-device
        self.mesh = mesh
        # window shards over 'seq' (1 = the classic whole-window engine)
        self.seq_shards = seq_shards
        # the fused Pallas single-query kernel only runs single-device:
        # pallas_call has no SPMD partitioning rule, so under a mesh the
        # decode step keeps the einsum composition GSPMD can shard.  (The
        # kernel itself degrades to the same reference off-TPU — tier-1
        # CPU runs exercise that fallback on this very path.)
        fused = mesh is None
        self.uses_fused_attention = fused
        greedy = temperature <= 0.0
        sample = _make_sampler(temperature,
                               None if greedy else top_k,
                               None if greedy else top_p)
        is_stop = _make_stop_check(stop_tokens)
        min_new = min_new_tokens

        def stop_gate(tok, new_count):
            # a stop token only freezes once the row has emitted
            # `min_new_tokens` tokens INCLUDING it; `new_count` is that
            # count (a python int at prefill, traced in segment scans)
            if min_new <= 1:
                return is_stop(tok)
            return is_stop(tok) & (new_count >= min_new)

        def prefill_impl(variables, prompts, true_len, live, row_keys):
            params = variables["params"]
            b, p = prompts.shape
            w0 = _round_up(p + 1, chunk)
            dh = module.d_model // module.n_heads
            caches = [(_hint_kv(jnp.zeros((b, w0, module.n_heads, dh),
                                          module.dtype)),
                       _hint_kv(jnp.zeros((b, w0, module.n_heads, dh),
                                          module.dtype)))
                      for _ in range(module.n_layers)]
            logits, caches = _forward_with_cache(params, prompts, caches,
                                                 0, module)
            last = jnp.take_along_axis(
                logits, (true_len - 1)[:, None, None], axis=1)[:, 0]
            tok = sample(last, row_keys, 0)
            done = ~live | stop_gate(tok, 1)
            if cache_dtype == "int8":
                # quantize-on-write at prefill granularity: the prompt's
                # whole cache quantizes once here, decode steps quantize
                # each new token inside _decode_block
                caches = [tuple(_hint_kv(c)
                                for c in _quantize_cache(kc, vc))
                          for kc, vc in caches]
            return tok, done, caches

        def segment_impl(seg_len, window, variables, caches, tok, done,
                         true_len, bucket, t0, row_keys):
            params = variables["params"]
            caches = [tuple(_hint_kv(_grow_cache(c, window)) for c in layer)
                      for layer in caches]
            slots = jnp.arange(window)

            def step(carry, s_off):
                tok, done, caches = carry
                t = t0 + s_off
                slot = bucket + t
                pos = true_len + t
                visible = ((slots[None, :] < true_len[:, None])
                           | ((slots[None, :] >= bucket)
                              & (slots[None, :] <= slot)))
                logits, caches = _decode_step(params, tok, pos, slot,
                                              caches, visible, module,
                                              cache_dtype, fused)
                nxt = sample(logits, row_keys, t + 1)
                nxt = jnp.where(done, tok, nxt)
                return (nxt, done | stop_gate(nxt, t + 2), caches), tok

            (tok, done, caches), toks = lax.scan(
                step, (tok, done, caches), jnp.arange(seg_len))
            return caches, toks.transpose(1, 0), tok, done

        if seq_shards > 1:
            # SEQ-SHARDED engine: replace the prefill/segment impls with
            # shard_map'd equivalents before the meshed wrappers below
            # close over the names.  Prefill runs DISTRIBUTED BLOCKWISE
            # (ring attention over the prompt slabs — wall clock ~1/n);
            # decode keeps the host segment loop identical but merges
            # per-chip softmax stats across 'seq' each step.
            from jax.sharding import PartitionSpec as P
            from mmlspark_tpu.parallel.ring import _shard_map
            tok_spec = P(DATA_AXIS, SEQ_AXIS)
            row_spec = P(DATA_AXIS)

            def _seq_cache_specs(caches):
                return [tuple(SEQ_KV_CACHE_SPEC if c.ndim == 4
                              else SEQ_KV_SCALE_SPEC for c in layer)
                        for layer in caches]

            def seq_prefill_impl(variables, prompts, true_len, live,
                                 row_keys):
                params = variables["params"]
                p = prompts.shape[1]
                w0 = _round_up(p + 1, chunk)
                dtype = module.dtype

                def local_fwd(params, tokens):
                    s_l = tokens.shape[1]
                    lo = lax.axis_index(SEQ_AXIS) * s_l
                    # SHARED positions 0..p-1 (the global slab offset),
                    # exactly _forward_with_cache's position stream —
                    # causal masking alone makes the per-row true_len-1
                    # logit gather correct
                    positions = lo + jnp.arange(s_l)
                    emb = (params["tok_embed"]["embedding"][tokens]
                           + params["pos_embed"]["embedding"][positions][
                               None])
                    x = emb.astype(dtype)
                    kvs = []
                    for i in range(module.n_layers):
                        x, k_l, v_l = _seq_prefill_block(
                            module, params[f"block{i}_w"], x, dtype,
                            SEQ_AXIS)
                        kvs.append((k_l.astype(dtype), v_l.astype(dtype)))
                    x = _ln(params["final_norm_w"], x, dtype)
                    logits = _dense(params["lm_head"], x,
                                    dtype).astype(jnp.float32)
                    return logits, kvs

                logits, kvs = _shard_map(
                    local_fwd, mesh=mesh,
                    in_specs=(P(), tok_spec),
                    out_specs=(P(DATA_AXIS, SEQ_AXIS, None),
                               SEQ_KV_CACHE_SPEC))(params, prompts)
                last = jnp.take_along_axis(
                    logits, (true_len - 1)[:, None, None], axis=1)[:, 0]
                tok = sample(last, row_keys, 0)
                done = ~live | stop_gate(tok, 1)
                # the cache window (w0, chunk-aligned) has DIFFERENT seq
                # partition boundaries than the prompt (p): pad outside
                # the shard_map and let GSPMD reshard once against the
                # hint — not inside, where slab widths would disagree
                caches = [(_hint_seq_kv(_grow_cache(k_l, w0)),
                           _hint_seq_kv(_grow_cache(v_l, w0)))
                          for k_l, v_l in kvs]
                if cache_dtype == "int8":
                    caches = [tuple(_hint_seq_kv(c)
                                    for c in _quantize_cache(kc, vc))
                              for kc, vc in caches]
                return tok, done, caches

            def seq_segment_impl(seg_len, window, variables, caches, tok,
                                 done, true_len, bucket, t0, row_keys):
                params = variables["params"]
                caches = [tuple(_hint_seq_kv(_grow_cache(c, window))
                                for c in layer) for layer in caches]
                cache_specs = _seq_cache_specs(caches)
                # typed PRNG keys are an extended dtype shard_map can't
                # always carry (jax 0.4.x): thread the raw uint32 key
                # data through and rebuild inside
                rk = jax.random.key_data(row_keys)

                def local_seg(params, caches, tok, done, true_len, bucket,
                              t0, rk):
                    row_keys = jax.random.wrap_key_data(rk)
                    w_l = caches[0][0].shape[1]
                    lo = lax.axis_index(SEQ_AXIS) * w_l
                    slots = lo + jnp.arange(w_l)

                    def step(carry, s_off):
                        tok, done, caches = carry
                        t = t0 + s_off
                        slot = bucket + t
                        pos = true_len + t
                        visible = ((slots[None, :] < true_len[:, None])
                                   | ((slots[None, :] >= bucket)
                                      & (slots[None, :] <= slot)))
                        logits, caches = _seq_decode_step(
                            params, tok, pos, slot, lo, caches, visible,
                            module, cache_dtype, SEQ_AXIS)
                        nxt = sample(logits, row_keys, t + 1)
                        nxt = jnp.where(done, tok, nxt)
                        return (nxt, done | stop_gate(nxt, t + 2),
                                caches), tok

                    (tok, done, caches), toks = lax.scan(
                        step, (tok, done, caches), jnp.arange(seg_len))
                    return caches, toks.transpose(1, 0), tok, done

                return _shard_map(
                    local_seg, mesh=mesh,
                    in_specs=(P(), cache_specs, row_spec, row_spec,
                              row_spec, P(), P(), P(DATA_AXIS, None)),
                    out_specs=(cache_specs, P(DATA_AXIS, None), row_spec,
                               row_spec))(
                    params, caches, tok, done, true_len, bucket, t0, rk)

            prefill_impl = seq_prefill_impl
            segment_impl = seq_segment_impl

        row_sample = _make_row_sampler(temperature,
                                       None if greedy else top_k,
                                       None if greedy else top_p)

        def serve_segment_impl(seg_len, window, variables, caches, tok,
                               done, true_len, budget, bucket, t_row,
                               row_keys):
            """The continuous-batching decode segment (serve/engine.py):
            rows carry PER-ROW step offsets `t_row` (joined rows start at
            0 while resident rows are mid-generation) and per-row token
            budgets, so one compiled program advances a mixed-age batch
            `seg_len` steps.  Rows freeze on stop/budget/done exactly as
            the uniform-step segment; frozen rows' writes land in their
            own cache row only and their emissions repeat the frozen
            token (the engine's per-row emit counters ignore them)."""
            params = variables["params"]
            caches = [tuple(_hint_kv(_grow_cache(c, window)) for c in layer)
                      for layer in caches]
            slots_axis = jnp.arange(window)
            max_pos = module.max_len - 1

            def step(carry, s_off):
                tok, done, caches = carry
                t = t_row + s_off                     # (B,) per-row step
                slot = jnp.minimum(bucket + t, window - 1)
                pos = jnp.minimum(true_len + t, max_pos)
                visible = ((slots_axis[None, :] < true_len[:, None])
                           | ((slots_axis[None, :] >= bucket)
                              & (slots_axis[None, :] <= slot[:, None])))
                logits, caches = _decode_step_rows(
                    params, tok, pos, slot, caches, visible, module,
                    cache_dtype, fused)
                nxt = row_sample(logits, row_keys, t + 1)
                nxt = jnp.where(done, tok, nxt)
                done = done | stop_gate(nxt, t + 2) | (t + 1 >= budget)
                return (nxt, done, caches), nxt

            (tok, done, caches), toks = lax.scan(
                step, (tok, done, caches), jnp.arange(seg_len))
            return caches, toks.transpose(1, 0), tok, done

        def prefill_chunk0_impl(w0, variables, tokens, true_len):
            """First chunk of a CHUNKED prefill (offset 0): allocates the
            window-`w0` caches and seeds the running last-prompt-position
            logits.  Chunking splits the prompt forward so the serving
            engine can interleave it with resident decode segments — a
            long prompt stops stalling running requests."""
            params = variables["params"]
            b, cl = tokens.shape
            dh = module.d_model // module.n_heads
            caches = [(_hint_kv(jnp.zeros((b, w0, module.n_heads, dh),
                                          module.dtype)),
                       _hint_kv(jnp.zeros((b, w0, module.n_heads, dh),
                                          module.dtype)))
                      for _ in range(module.n_layers)]
            logits, caches = _forward_with_cache(params, tokens, caches,
                                                 0, module)
            idx = jnp.clip(true_len - 1, 0, cl - 1)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]
            return caches, last

        def prefill_chunk_impl(variables, tokens, caches, last, true_len,
                               c0):
            """One later prompt chunk at TRACED offset `c0`: the dense
            `_block_with_cache` path works at any position, so every
            chunk index shares ONE compiled program per shape class.
            Rows whose last prompt token falls inside this chunk update
            the running last-position logits."""
            params = variables["params"]
            cl = tokens.shape[1]
            logits, caches = _forward_with_cache(params, tokens, caches,
                                                 c0, module)
            idx = jnp.clip(true_len - 1 - c0, 0, cl - 1)
            cand = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]
            here = (true_len - 1 >= c0) & (true_len - 1 < c0 + cl)
            last = jnp.where(here[:, None], cand, last)
            return caches, last

        def prefill_finish_impl(caches, last, live, row_keys):
            """Close a chunked prefill: sample the first token and (int8
            mode) quantize the whole prompt cache — the same
            (tok, done, caches) contract as `prefill_impl`."""
            tok = sample(last, row_keys, 0)
            done = ~live | stop_gate(tok, 1)
            if cache_dtype == "int8":
                caches = [tuple(_hint_kv(c)
                                for c in _quantize_cache(kc, vc))
                          for kc, vc in caches]
            return tok, done, caches

        def resume_init_impl(w0, row_caches):
            """Open a RESUMED chunked prefill from a donor prefix row
            (serve/prefix_cache.py): dequantize int8 donor slots back to
            model dtype (suffix chunks keep writing through the same
            model-dtype cache the fresh path uses; `prefill_finish_impl`
            re-quantizes the whole window, and quantize_kv's round-trip
            idempotency keeps the stored prefix bytes identical), grow
            to the bucket window, and zero the running last-position
            logits — the matched prefix is always strictly inside the
            prompt, so a later chunk's `here` mask recomputes them."""
            caches = []
            for layer in row_caches:
                if len(layer) == 4:
                    kq, ks, vq, vs = layer
                    k = (kq.astype(jnp.float32)
                         * ks[..., None]).astype(module.dtype)
                    v = (vq.astype(jnp.float32)
                         * vs[..., None]).astype(module.dtype)
                else:
                    k, v = layer
                caches.append((_hint_kv(_grow_cache(k, w0)),
                               _hint_kv(_grow_cache(v, w0))))
            b = row_caches[0][0].shape[0]
            last = jnp.zeros((b, module.vocab_size), jnp.float32)
            return caches, last

        def draft_prefill_impl(draft_variables, prompts):
            """Prefill the DRAFT model's cache over the prompt
            (speculative decoding) — same window arithmetic as the
            target prefill, no sampling: the draft's first proposal
            comes from its first round step.  Draft caches stay
            model-dtype (the draft is latency-sized; int8's bandwidth
            win is a target-cache story) and replicate their heads under
            a mesh (DRAFT_KV_CACHE_SPEC)."""
            dm = draft_module
            params = draft_variables["params"]
            b, p = prompts.shape
            w0 = _round_up(p + 1, chunk)
            dh = dm.d_model // dm.n_heads
            caches = [(_hint_draft_kv(jnp.zeros((b, w0, dm.n_heads, dh),
                                                dm.dtype)),
                       _hint_draft_kv(jnp.zeros((b, w0, dm.n_heads, dh),
                                                dm.dtype)))
                      for _ in range(dm.n_layers)]
            _, caches = _forward_with_cache(params, prompts, caches, 0,
                                            dm)
            return caches

        k_spec = spec_tokens

        def spec_round_impl(window, variables, draft_variables, caches,
                            draft_caches, tok, done, true_len, budget,
                            bucket, t_row, round_idx, row_keys):
            """One speculative round over a mixed-age batch (generate()
            and the serving engine share this program): the draft model
            proposes `spec_tokens` tokens with k+1 cheap single-token
            steps (the extra step back-fills the last proposal's
            draft-cache slot, so the draft never attends a zero slot),
            ONE target forward scores every proposal
            (`_verify_step_rows`), and the agreeing prefix commits.

            Greedy mode accepts while the proposal equals the target
            argmax and appends the target's own next token — the
            committed stream IS the target's greedy chain by
            construction.  Sampler mode runs standard rejection
            sampling: accept d ~ q(draft) with probability min(1,
            p(d)/q(d)); on rejection draw from the residual
            max(p - q, 0)/Z — each committed token is distributed
            exactly as a target-model draw, whatever the draft proposes.

            Rejected proposals leave garbage K/V past a row's committed
            frontier; visibility is strictly causal in committed slots,
            so those bytes are never read, and the next round overwrites
            them in order.  Returns (caches, draft_caches,
            toks (B, k+1), counts (B,), tok, done, accepted (B,)):
            `counts[r]` leading entries of row r's `toks` are real
            committed tokens (the rest repeat the frozen token);
            `accepted` is the raw draft/target agreement length for
            acceptance-rate telemetry."""
            params = variables["params"]
            dparams = draft_variables["params"]
            caches = [tuple(_hint_kv(_grow_cache(c, window))
                            for c in layer) for layer in caches]
            draft_caches = [tuple(_hint_draft_kv(_grow_cache(c, window))
                                  for c in layer)
                            for layer in draft_caches]
            b = tok.shape[0]
            s = k_spec + 1
            slots_axis = jnp.arange(window)
            max_pos = module.max_len - 1
            sampling = not greedy

            # -- draft: k+1 single-token steps (proposals from the first
            # k; the last only writes K/V so the draft cache covers
            # every slot its next round will attend) --
            d_toks = []
            d_dists = []
            cur = tok
            for j in range(s):
                t = t_row + j
                slot = jnp.minimum(bucket + t, window - 1)
                pos = jnp.minimum(true_len + t, max_pos)
                visible = ((slots_axis[None, :] < true_len[:, None])
                           | ((slots_axis[None, :] >= bucket)
                              & (slots_axis[None, :] <= slot[:, None])))
                dlogits, draft_caches = _decode_step_rows(
                    dparams, cur, pos, slot, draft_caches, visible,
                    draft_module, "model")
                if j == k_spec:
                    break          # K/V back-fill only; proposal unused
                if sampling:
                    fd = filter_logits(dlogits / temperature, top_k,
                                       top_p)
                    keys = jax.vmap(
                        lambda rk, jj=j: jax.random.fold_in(
                            jax.random.fold_in(
                                rk, _SPEC_DRAFT_STREAM + round_idx),
                            jj))(row_keys)
                    nxt = jax.vmap(jax.random.categorical)(
                        keys, fd).astype(jnp.int32)
                    d_dists.append(jax.nn.softmax(fd, axis=-1))
                else:
                    nxt = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
                d_toks.append(nxt)
                cur = nxt
            d = jnp.stack(d_toks, axis=1)                       # (B, k)

            # -- verify: one target forward over [tok, d_1..d_k]; token
            # index t_row+j's K/V lands at slot bucket+t_row+j, the same
            # invariant the per-step path keeps --
            xs = jnp.concatenate([tok[:, None], d], axis=1)     # (B, S)
            slots0 = jnp.minimum(bucket + t_row, window - s)
            q_idx = jnp.arange(s)
            vis = ((slots_axis[None, None, :] < true_len[:, None, None])
                   | ((slots_axis[None, None, :] >= bucket)
                      & (slots_axis[None, None, :]
                         <= (slots0[:, None]
                             + q_idx[None, :])[:, :, None])))
            logits, caches = _verify_step_rows(
                params, xs, true_len + t_row, slots0, caches, vis,
                module, cache_dtype)

            # -- accept --
            if sampling:
                ft = filter_logits(logits / temperature, top_k, top_p)
                pt = jax.nn.softmax(ft, axis=-1)                # (B,S,V)
                qd = jnp.stack(d_dists, axis=1)                 # (B,k,V)
                pt_d = jnp.take_along_axis(
                    pt[:, :k_spec], d[..., None], axis=2)[..., 0]
                qd_d = jnp.take_along_axis(
                    qd, d[..., None], axis=2)[..., 0]
                coin_keys = jax.vmap(lambda rk: jax.random.fold_in(
                    rk, _SPEC_COIN_STREAM + round_idx))(row_keys)
                u = jax.vmap(lambda kk: jax.random.uniform(
                    kk, (k_spec,)))(coin_keys)
                accept = u * jnp.maximum(qd_d, 1e-30) < pt_d    # (B, k)
                n_acc = jnp.cumprod(accept.astype(jnp.int32),
                                    axis=1).sum(axis=1)
                # residual at every position; position k's draft dist is
                # empty, so its residual is the target dist itself — the
                # all-accepted bonus draw falls out of the same formula
                qd_ext = jnp.concatenate(
                    [qd, jnp.zeros((b, 1, qd.shape[-1]), qd.dtype)],
                    axis=1)
                res = jnp.maximum(pt - qd_ext, 0.0)
                mass = res.sum(axis=-1, keepdims=True)
                res = jnp.where(mass > 1e-30, res, pt)  # p == q guard
                fkeys = jax.vmap(lambda rk: jax.vmap(
                    lambda jj: jax.random.fold_in(
                        jax.random.fold_in(
                            rk, _SPEC_FIX_STREAM + round_idx), jj))(
                    jnp.arange(s)))(row_keys)
                fix = jax.vmap(jax.vmap(
                    lambda kk, rr: jax.random.categorical(
                        kk, jnp.where(rr > 0,
                                      jnp.log(jnp.maximum(rr, 1e-38)),
                                      NEG_INF))))(fkeys, res)
                corr = jnp.take_along_axis(
                    fix.astype(jnp.int32), n_acc[:, None], axis=1)[:, 0]
            else:
                g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                agree = (d == g[:, :k_spec])
                n_acc = jnp.cumprod(agree.astype(jnp.int32),
                                    axis=1).sum(axis=1)
                corr = jnp.take_along_axis(
                    g, n_acc[:, None], axis=1)[:, 0]

            # -- commit: positions 0..n are real (accepted prefix plus
            # the correction/bonus token); stop/budget freezes evolve
            # exactly as the per-step scan's --
            i_idx = jnp.arange(s)[None, :]
            d_pad = jnp.concatenate(
                [d, jnp.zeros((b, 1), jnp.int32)], axis=1)
            seq0 = jnp.where(i_idx < n_acc[:, None], d_pad,
                             corr[:, None])
            entry_done = done
            out_toks = []
            cur = tok
            count = jnp.zeros(b, jnp.int32)
            for i in range(s):
                live_pos = (~done) & (i <= n_acc)
                cur = jnp.where(live_pos, seq0[:, i], cur)
                idx = t_row + 1 + i          # global token index (B,)
                done = (done
                        | (live_pos & stop_gate(cur, idx + 1))
                        | (live_pos & (idx >= budget)))
                count = count + live_pos.astype(jnp.int32)
                out_toks.append(cur)
            toks_out = jnp.stack(out_toks, axis=1)              # (B, S)
            accepted = jnp.where(entry_done, 0, n_acc).astype(jnp.int32)
            return (caches, draft_caches, toks_out, count, cur, done,
                    accepted)

        # jit the meshed wrappers, not the impls: tracing runs the body,
        # so use_mesh(mesh) bakes the KV hints into every compiled
        # program (and the attributes stay jit objects —
        # capture_program_cost .lower()s them)
        def prefill_meshed(variables, prompts, true_len, live, row_keys):
            with use_mesh(mesh):
                return prefill_impl(variables, prompts, true_len, live,
                                    row_keys)

        def segment_meshed(seg_len, window, *args):
            with use_mesh(mesh):
                return segment_impl(seg_len, window, *args)

        def serve_segment_meshed(seg_len, window, *args):
            with use_mesh(mesh):
                return serve_segment_impl(seg_len, window, *args)

        def prefill_chunk0_meshed(w0, variables, tokens, true_len):
            with use_mesh(mesh):
                return prefill_chunk0_impl(w0, variables, tokens,
                                           true_len)

        def prefill_chunk_meshed(*args):
            with use_mesh(mesh):
                return prefill_chunk_impl(*args)

        def prefill_finish_meshed(*args):
            with use_mesh(mesh):
                return prefill_finish_impl(*args)

        def resume_init_meshed(w0, row_caches):
            with use_mesh(mesh):
                return resume_init_impl(w0, row_caches)

        self._prefill = jax.jit(prefill_meshed)
        self._segment = jax.jit(segment_meshed, static_argnums=(0, 1))
        self._serve_segment = jax.jit(serve_segment_meshed,
                                      static_argnums=(0, 1))
        self._prefill_chunk0 = jax.jit(prefill_chunk0_meshed,
                                       static_argnums=(0,))
        self._prefill_chunk = jax.jit(prefill_chunk_meshed)
        self._prefill_finish = jax.jit(prefill_finish_meshed)
        self._resume_init = jax.jit(resume_init_meshed,
                                    static_argnums=(0,))
        if spec_tokens:
            def draft_prefill_meshed(draft_variables, prompts):
                with use_mesh(mesh):
                    return draft_prefill_impl(draft_variables, prompts)

            def spec_round_meshed(window, *args):
                with use_mesh(mesh):
                    return spec_round_impl(window, *args)

            self._draft_prefill = jax.jit(draft_prefill_meshed)
            self._spec_round = jax.jit(spec_round_meshed,
                                       static_argnums=(0,))
        self._programs: set = set()
        self._program_costs: dict = {}  # program key -> captured cost row
        # (captured once at the recompile; replayed into every later
        # run_telemetry block so warm-engine runs still get roofline rows)
        self.last_segments_run = 0
        self.last_new_tokens_computed = 0
        self.last_exit_checks_skipped = 0
        self.last_spec_rounds = 0
        self.last_spec_drafted = 0
        self.last_spec_accepted = 0
        self.last_spec_acceptance = 0.0

    def bucket_for(self, prompt_len: int) -> int:
        return bucket_length(prompt_len, self.module.max_len,
                             self.max_new_tokens, self.min_bucket)

    # -- serving hooks (serve/engine.py) ---------------------------------
    # The continuous-batching scheduler drives the engine's compiled
    # programs directly at segment granularity: prefill a join cohort,
    # splice its cache rows into the resident batch, advance everyone one
    # mixed-age segment, cancel/harvest at the boundary.  All three hooks
    # keep the jit shape-class discipline (and the recompile telemetry)
    # of the batch path.

    def _refuse_seq(self, hook: str) -> None:
        """Serving hooks refuse a seq-sharded engine: continuous
        batching's per-row cache writes, row splices, and prefix-cache
        handoff pages all assume whole-window rows on one device.  Use
        `generate()` / TextGenerator for seq-parallel long-context
        decode."""
        if self.seq_shards > 1:
            raise ValueError(
                f"{hook} does not support a seq-sharded engine (mesh "
                f"seq={self.seq_shards}): serving assumes whole-window "
                "cache rows; use DecodeEngine.generate / TextGenerator "
                "for seq-parallel long-context decode")

    def serve_prefill(self, variables, prompts, true_len, live, row_keys):
        """Prefill one join cohort: prompts (N, bucket) right-padded,
        per-row true lengths, `live=False` born-done pad rows, per-row
        sampling keys.  Returns (tok, done, caches) — the cohort's first
        generated token per row and its bucket-window caches, ready to
        splice into a resident batch with `merge_cache_rows`."""
        self._refuse_seq("serve_prefill")
        b, p = prompts.shape
        key = ("prefill", b, p)
        tok, done, caches = self._prefill(
            variables, jnp.asarray(prompts), jnp.asarray(true_len),
            jnp.asarray(live), row_keys)
        self._program(*key)
        return tok, done, caches

    def serve_step(self, variables, caches, tok, done, true_len, budget,
                   bucket: int, t_row, row_keys, seg_len: int,
                   window: int):
        """Advance a mixed-age resident batch `seg_len` decode steps
        (models/generate.py serve_segment_impl): per-row step offsets
        `t_row` and per-row token budgets; returns (caches, toks
        (B, seg_len), tok, done).  `window` must cover the highest slot
        any live row writes: bucket + max(t_row) + seg_len, chunk-rounded
        (`serve_window`)."""
        self._refuse_seq("serve_step")
        b = int(tok.shape[0])
        w_in = int(caches[0][0].shape[1])
        # a resident cache never shrinks: joins after long-running rows
        # completed can ask for a smaller cover than the batch already
        # holds — the segment then just attends the existing width
        window = max(int(window), w_in)
        key = ("serve_segment", b, w_in, window, seg_len)
        out = self._serve_segment(
            seg_len, window, variables, caches, tok, done,
            jnp.asarray(true_len), jnp.asarray(budget, jnp.int32),
            jnp.asarray(bucket, jnp.int32),
            jnp.asarray(t_row, jnp.int32), row_keys)
        self._program(*key)
        return out

    def serve_window(self, bucket: int, max_t: int, seg_len: int) -> int:
        """The chunk-rounded cache window covering a segment whose oldest
        live row sits at step `max_t`, capped at the model's position
        budget (frozen rows past the cap clamp their writes in-window)."""
        need = min(bucket + max_t + seg_len, self.module.max_len)
        return _round_up(max(need, bucket + 1), self.chunk)

    def serve_prefill_chunks(self, bucket: int) -> int:
        """How many chunks a chunked prefill of this bucket runs (0 = the
        whole-prompt program applies: chunking off, bucket no larger than
        the chunk, or a bucket the chunk doesn't divide — buckets are
        powers of two, so any power-of-two `prefill_chunk` divides every
        bucket it's smaller than)."""
        cl = self.prefill_chunk
        if not cl or bucket <= cl or bucket % cl:
            return 0
        return bucket // cl

    def serve_prefill_chunk(self, variables, prompts, true_len,
                            index: int, state):
        """Run chunk `index` of a join cohort's chunked prefill; `state`
        is None for chunk 0, else the (caches, last_logits) carry the
        previous chunk returned.  The serving engine interleaves these
        calls with resident decode segments, so a long prompt never
        stalls running requests (serve/engine.py)."""
        self._refuse_seq("serve_prefill_chunk")
        prompts = np.asarray(prompts)
        b, p = prompts.shape
        cl = self.prefill_chunk
        w0 = _round_up(p + 1, self.chunk)
        tl = jnp.asarray(true_len)
        tokens = jnp.asarray(prompts[:, index * cl:(index + 1) * cl])
        if index == 0:
            state = self._prefill_chunk0(w0, variables, tokens, tl)
            self._program("prefill_chunk0", b, cl, w0)
        else:
            caches, last = state
            state = self._prefill_chunk(variables, tokens, caches, last,
                                        tl, jnp.asarray(index * cl,
                                                        jnp.int32))
            self._program("prefill_chunk", b, cl, w0)
        return state

    def serve_prefill_finish(self, state, live, row_keys):
        """Close a chunked serve prefill: the same (tok, done, caches)
        contract as `serve_prefill`, ready for `merge_cache_rows`."""
        self._refuse_seq("serve_prefill_finish")
        caches, last = state
        b = int(last.shape[0])
        w0 = int(caches[0][0].shape[1])
        tok, done, caches = self._prefill_finish(caches, last,
                                                 jnp.asarray(live),
                                                 row_keys)
        self._program("prefill_finish", b, w0)
        return tok, done, caches

    def serve_resume_chunks(self, bucket: int, prefix_len: int) -> int:
        """How many SUFFIX chunks a chunk-interleaved resume from a
        `prefix_len`-token donor prefix runs (0 = resume inline via
        `serve_prefill_resume`: chunking off for this bucket, or the
        prefix is not prefill_chunk-aligned)."""
        total = self.serve_prefill_chunks(bucket)
        cl = self.prefill_chunk
        if (not total or prefix_len <= 0 or prefix_len >= bucket
                or prefix_len % cl):
            return 0
        return total - prefix_len // cl

    def serve_resume_init(self, row_caches, bucket: int):
        """Open a resumed prefill from donor prefix rows (the prefix
        pool's spliced-together chunk payloads, slot width = matched
        prefix): dequantize/grow to the bucket window and zero the
        running logits — a (caches, last) state `serve_prefill_chunk`
        (index >= 1) and `serve_prefill_finish` continue verbatim."""
        self._refuse_seq("serve_resume_init")
        w0 = _round_up(bucket + 1, self.chunk)
        b = int(row_caches[0][0].shape[0])
        n = int(row_caches[0][0].shape[1])
        state = self._resume_init(w0, row_caches)
        self._program("resume_init", b, n, w0, len(row_caches[0]))
        return state

    def serve_prefill_resume(self, variables, prompts, true_len,
                             prefix_len: int, row_caches, live, row_keys):
        """Prefill ONLY the novel suffix of a prompt whose first
        `prefix_len` tokens have donor cache rows (prefix pool hit):
        one `prefill_chunk` call at traced offset `prefix_len` over the
        whole suffix, then the standard finish.  The dense full-cache
        attention path makes the suffix forward attend the donor slots
        exactly as a fresh prefill would its own — byte-identical
        greedy outputs are the contract (model-dtype rows exact; int8
        rows carry the documented quantization caveat).  Same
        (tok, done, caches) contract as `serve_prefill`."""
        self._refuse_seq("serve_prefill_resume")
        prompts = np.asarray(prompts)
        b, p = prompts.shape
        if not 0 < prefix_len < p:
            raise ValueError(
                f"prefix_len ({prefix_len}) must be inside the bucket "
                f"({p})")
        caches, last = self.serve_resume_init(row_caches, p)
        w0 = int(caches[0][0].shape[1])
        tokens = jnp.asarray(prompts[:, prefix_len:])
        state = self._prefill_chunk(
            variables, tokens, caches, last, jnp.asarray(true_len),
            jnp.asarray(prefix_len, jnp.int32))
        self._program("prefill_chunk", b, p - prefix_len, w0)
        return self.serve_prefill_finish(state, live, row_keys)

    def serve_draft_prefill(self, draft_variables, prompts):
        """Prefill the draft model's cache for a join cohort (speculative
        serving): returns the draft caches to splice alongside the target
        caches (`merge_cache_rows` handles both)."""
        self._refuse_seq("serve_draft_prefill")
        prompts = np.asarray(prompts)
        b, p = prompts.shape
        caches = self._draft_prefill(draft_variables,
                                     jnp.asarray(prompts))
        self._program("draft_prefill", b, p)
        return caches

    def serve_spec_round(self, variables, draft_variables, caches,
                         draft_caches, tok, done, true_len, budget,
                         bucket: int, t_row, round_idx: int, row_keys,
                         window: int):
        """One speculative round over the resident batch — the SAME
        compiled program as the batch path (per-row step offsets and
        budgets from the start).  Returns (caches, draft_caches, toks
        (B, k+1), counts, tok, done, accepted); the engine advances each
        row's t_row by its count and emits the counted prefix."""
        self._refuse_seq("serve_spec_round")
        b = int(tok.shape[0])
        w_in = int(caches[0][0].shape[1])
        window = max(int(window), w_in,
                     int(draft_caches[0][0].shape[1]))
        key = ("spec_round", b, w_in, window, self.spec_tokens)
        out = self._spec_round(
            window, variables, draft_variables, caches, draft_caches,
            tok, done, jnp.asarray(true_len),
            jnp.asarray(budget, jnp.int32),
            jnp.asarray(bucket, jnp.int32),
            jnp.asarray(t_row, jnp.int32),
            jnp.asarray(round_idx, jnp.int32), row_keys)
        self._program(*key)
        return out

    @staticmethod
    def merge_cache_rows(dst_caches, src_caches, dst_rows, src_rows,
                         mesh=None):
        """Splice cohort cache rows into a resident batch: row
        `src_rows[i]` of `src_caches` replaces row `dst_rows[i]` of
        `dst_caches`.  Both sides are grown to the wider window first
        (zero-pad, `_grow_cache`), so a freshly prefilled cohort joins a
        long-running batch without recompiling anything.  Works for both
        cache layouts (2-tuple model-dtype, 4-tuple int8): every leaf is
        row-indexed on axis 0.  One jitted program per (windows, rows)
        shape class — a join is a handful of fused scatters, not a
        cascade of eager ops.  Pass `mesh` (an engine's `.mesh`) so the
        merge program's KV hints trace against it — sharded resident
        caches then stay sharded through every join."""
        if mesh is not None and int(mesh.shape.get(SEQ_AXIS, 1)) > 1:
            raise ValueError(
                "merge_cache_rows refuses seq-sharded caches (mesh "
                "seq>1): row splicing assumes whole-window rows; gather "
                "a row explicitly (serialize_cache_row np.asarray-"
                "gathers the window) or decode outside the serving join "
                "path")
        di = jnp.asarray(dst_rows, jnp.int32)
        si = jnp.asarray(src_rows, jnp.int32)
        with use_mesh(mesh):
            return _merge_cache_rows_jit(dst_caches, src_caches, di, si)

    @property
    def compiled_programs(self) -> int:
        """Distinct compiled shape classes (prefill + segment) so far —
        mirrors jit's specialization key, so it counts real XLA programs."""
        return len(self._programs)

    def _program(self, *key) -> None:
        """Register one executed shape class; a NEW class is a recompile
        and surfaces as a telemetry `compile` event (zero-cost inactive)."""
        if key not in self._programs:
            self._programs.add(key)
            trace_event("recompile", cat="compile", where="decode",
                        program=str(key))

    def _run_chunked_prefill(self, variables, prompts, true_len, live,
                             row_keys):
        """Host loop for a CHUNKED prefill: chunk 0 allocates, later
        chunks share one compiled program (traced offset), finish
        samples and (int8) quantizes.  Same (tok, done, caches) contract
        — and the same first token — as the whole-prompt program."""
        prompts = jnp.asarray(prompts)
        b, p = int(prompts.shape[0]), int(prompts.shape[1])
        cl = self.prefill_chunk
        w0 = _round_up(p + 1, self.chunk)
        tl = jnp.asarray(true_len)
        with trace_span("decode.prefill_chunk", cat="bucket", bucket=p,
                        batch=b, chunk=cl, index=0):
            state = self._prefill_chunk0(w0, variables, prompts[:, :cl],
                                         tl)
        self._program("prefill_chunk0", b, cl, w0)
        for ci in range(1, p // cl):
            caches, last = state
            with trace_span("decode.prefill_chunk", cat="bucket",
                            bucket=p, batch=b, chunk=cl, index=ci):
                state = self._prefill_chunk(
                    variables, prompts[:, ci * cl:(ci + 1) * cl],
                    caches, last, tl, jnp.asarray(ci * cl, jnp.int32))
            self._program("prefill_chunk", b, cl, w0)
        caches, last = state
        tok, done, caches = self._prefill_finish(
            caches, last, jnp.asarray(live), row_keys)
        self._program("prefill_finish", b, w0)
        return tok, done, caches

    def _chunks_prefill(self, bucket: int) -> bool:
        return self.serve_prefill_chunks(bucket) > 0

    def generate(self, variables, prompts, true_len, *, rng=None,
                 row_ids=None, live=None,
                 draft_variables=None) -> np.ndarray:
        """Generate `max_new_tokens` per row: prompts (B, bucket) int32
        right-padded, true_len (B,) per-row prompt lengths.  Returns the
        GENERATED region (B, max_new_tokens) — after a row's first stop
        token the remaining slots repeat that token (and once every live
        row has stopped, the remaining segments are skipped entirely).

        `row_ids` is the stable per-row sampling-stream id (defaults to
        0..B-1); `live=False` rows (mesh shard padding) are born done so
        they never hold the batch open.  Arrays may be host numpy or
        already-placed device arrays (the mesh path shards them first).
        With `spec_tokens` set, `draft_variables` is required and decode
        runs draft/verify rounds instead of per-token segments — greedy
        outputs are byte-identical to the non-speculative path
        (test-pinned); sampled outputs draw from the same target
        distribution through rejection sampling, on disjoint RNG
        streams.
        """
        b, p = np.shape(prompts)[0], np.shape(prompts)[1]
        tl_host = np.asarray(true_len)
        if int(tl_host.max()) > p:
            raise ValueError(
                f"true_len ({int(tl_host.max())}) exceeds the prompt "
                f"bucket width ({p})")
        if int(tl_host.max()) + self.max_new_tokens > self.module.max_len:
            raise ValueError(
                f"prompt_len ({int(tl_host.max())}) + max_new_tokens "
                f"({self.max_new_tokens}) exceeds the model's max_len "
                f"({self.module.max_len})")
        if p % self.seq_shards:
            raise ValueError(
                f"prompt bucket ({p}) must divide by the mesh seq axis "
                f"({self.seq_shards}) for distributed blockwise prefill "
                "(pad the bucket — true_len already handles the tail)")
        base = rng if rng is not None else jax.random.key(0)
        ids = jnp.arange(b) if row_ids is None else jnp.asarray(row_ids)
        row_keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(ids)
        if live is None:
            live = np.ones(b, bool)
        timings = active_timings()
        run = active_run()
        if self.spec_tokens:
            if draft_variables is None:
                raise ValueError(
                    "this engine speculates (spec_tokens "
                    f"{self.spec_tokens}); generate() needs "
                    "draft_variables")
            return self._generate_speculative(
                variables, draft_variables, prompts, true_len, live,
                row_keys, b, p, timings, run)
        with trace_span("decode.generate", cat="phase", bucket=p, batch=b,
                        max_new_tokens=self.max_new_tokens):
            pf_key = ("prefill", b, p)
            pf_args = (variables, jnp.asarray(prompts),
                       jnp.asarray(true_len), jnp.asarray(live), row_keys)
            if self._chunks_prefill(p):
                with span_on(timings, "prefill"), \
                        trace_span("decode.prefill", cat="bucket",
                                   bucket=p, batch=b, chunked=True):
                    tok, done, caches = self._run_chunked_prefill(
                        variables, prompts, true_len, live, row_keys)
                    if timings is not None:
                        jax.block_until_ready(tok)
                psp = None
            else:
                if run is not None and pf_key not in self._programs:
                    # compile-time cost capture (observe/costmodel.py):
                    # once per program, with a synced probe execution —
                    # the live span below walls only the async dispatch
                    rec = capture_program_cost(self._prefill, pf_args,
                                               where="decode",
                                               program=pf_key,
                                               run=run, probe=True)
                    if rec is not None:
                        self._program_costs[pf_key] = rec
                with span_on(timings, "prefill"), \
                        trace_span("decode.prefill", cat="bucket",
                                   bucket=p, batch=b) as psp:
                    tok, done, caches = self._prefill(*pf_args)
                    if timings is not None:
                        jax.block_until_ready(tok)
                self._program(*pf_key)
            if run is not None and psp is not None:
                # replay the remembered cost row so warm-engine runs (no
                # recompile) still get roofline rows (idempotent)
                if pf_key in self._program_costs:
                    run.record_program_cost("decode", pf_key,
                                            self._program_costs[pf_key])
                run.add_program_time("decode", pf_key, psp.elapsed(),
                                     basis="dispatch")
            segs = decode_segments(p, self.max_new_tokens, self.chunk)
            check_exit = bool(self.stop_tokens)
            prev_w = _round_up(p + 1, self.chunk)
            parts = []
            segments_run = 0
            exit_checks_skipped = 0
            with span_on(timings, "decode"):
                for t0, seg_len, window in segs:
                    if check_exit and t0 + 1 < self.min_new_tokens:
                        # tokens 0..t0 exist, and a stop only freezes
                        # from token index min_new_tokens-1 on — no row
                        # can possibly be done, so skip the device->host
                        # sync outright (counted; gauge below)
                        exit_checks_skipped += 1
                    elif check_exit and bool(
                            np.asarray(jax.device_get(done)).all()):
                        trace_event("decode.early_exit", cat="decode",
                                    at_step=t0, batch=b,
                                    segments_skipped=len(segs)
                                    - segments_run)
                        break
                    seg_key = ("segment", b, prev_w, window, seg_len)
                    seg_args = (seg_len, window, variables, caches, tok,
                                done, jnp.asarray(true_len),
                                jnp.asarray(p, jnp.int32),
                                jnp.asarray(t0, jnp.int32), row_keys)
                    if run is not None and seg_key not in self._programs:
                        # captured BEFORE the call: the caches are
                        # rebound to window-grown outputs after it
                        rec = capture_program_cost(self._segment, seg_args,
                                                   where="decode",
                                                   program=seg_key, run=run,
                                                   probe=True,
                                                   static_argnums=(0, 1))
                        if rec is not None:
                            self._program_costs[seg_key] = rec
                    # occupancy: cache slots live after this segment over
                    # the slots the compiled step actually attends
                    with trace_span("decode.segment", cat="segment",
                                    window=window, seg_len=seg_len,
                                    step_offset=t0,
                                    occupancy=round(
                                        (p + t0 + seg_len) / window, 3)) \
                            as ssp:
                        caches, toks, tok, done = self._segment(*seg_args)
                    self._program(*seg_key)
                    if run is not None and ssp is not None:
                        if seg_key in self._program_costs:
                            run.record_program_cost(
                                "decode", seg_key,
                                self._program_costs[seg_key])
                        run.add_program_time("decode", seg_key,
                                             ssp.elapsed(),
                                             basis="dispatch")
                    prev_w = window
                    parts.append(toks)
                    segments_run += 1
                generated = np.concatenate(
                    [np.asarray(x) for x in parts]
                    + [np.asarray(tok)[:, None]], axis=1)
        if run is not None:
            run.gauge("decode.compiled_programs", self.compiled_programs)
            run.gauge("decode.early_exit_checks_skipped",
                      exit_checks_skipped)
        self.last_segments_run = segments_run
        self.last_new_tokens_computed = generated.shape[1]
        self.last_exit_checks_skipped = exit_checks_skipped
        if generated.shape[1] < self.max_new_tokens:
            # early exit: every row is frozen on its stop token — the fill
            # is exactly what the skipped segments would have emitted
            fill = np.repeat(np.asarray(tok)[:, None],
                             self.max_new_tokens - generated.shape[1], axis=1)
            generated = np.concatenate([generated, fill], axis=1)
        return generated.astype(np.int32)

    def _generate_speculative(self, variables, draft_variables, prompts,
                              true_len, live, row_keys, b, p, timings,
                              run) -> np.ndarray:
        """The speculative form of `generate`: target prefill (chunked or
        whole — the same programs), a draft prefill, then draft/verify
        rounds until every row freezes or fills its budget.  One round
        program serves every round; the cache window grows with the
        oldest row exactly as the serve path's does."""
        k = self.spec_tokens
        max_new = self.max_new_tokens
        with trace_span("decode.generate", cat="phase", bucket=p,
                        batch=b, max_new_tokens=max_new, spec_tokens=k):
            with span_on(timings, "prefill"), \
                    trace_span("decode.prefill", cat="bucket", bucket=p,
                               batch=b, speculative=True):
                if self._chunks_prefill(p):
                    tok, done, caches = self._run_chunked_prefill(
                        variables, prompts, true_len, live, row_keys)
                else:
                    tok, done, caches = self._prefill(
                        variables, jnp.asarray(prompts),
                        jnp.asarray(true_len), jnp.asarray(live),
                        row_keys)
                    self._program("prefill", b, p)
                dcaches = self._draft_prefill(draft_variables,
                                              jnp.asarray(prompts))
                self._program("draft_prefill", b, p)
                if timings is not None:
                    jax.block_until_ready(tok)
            out = np.zeros((b, max_new), np.int32)
            out[:, 0] = np.asarray(tok)
            emitted = np.ones(b, np.int64)
            t_row_h = np.zeros(b, np.int32)
            # freeze once a row's newest token index reaches max_new-1 —
            # the per-step scan's budget semantics (serve_segment_impl)
            budget = jnp.full(b, max_new - 1, jnp.int32)
            tl_dev = jnp.asarray(true_len)
            bucket_dev = jnp.asarray(p, jnp.int32)
            done_h = np.asarray(jax.device_get(done))
            drafted = 0
            accepted_total = 0
            rounds = 0
            with span_on(timings, "decode"):
                while not bool(done_h.all()):
                    w_in = int(caches[0][0].shape[1])
                    window = max(
                        self.serve_window(p, int(t_row_h.max()), k + 1),
                        w_in, int(dcaches[0][0].shape[1]))
                    key = ("spec_round", b, w_in, window, k)
                    with trace_span("decode.spec_round", cat="segment",
                                    window=window, round=rounds):
                        (caches, dcaches, toks, counts, tok, done,
                         acc) = self._spec_round(
                            window, variables, draft_variables, caches,
                            dcaches, tok, done, tl_dev, budget,
                            bucket_dev, jnp.asarray(t_row_h),
                            jnp.asarray(rounds, jnp.int32), row_keys)
                    self._program(*key)
                    toks_h = np.asarray(toks)
                    counts_h = np.asarray(counts)
                    live_rows = counts_h > 0
                    drafted += int(live_rows.sum()) * k
                    accepted_total += int(np.asarray(acc).sum())
                    for r in np.nonzero(live_rows)[0]:
                        take = min(int(counts_h[r]),
                                   max_new - int(emitted[r]))
                        if take > 0:
                            out[r, emitted[r]:emitted[r] + take] = \
                                toks_h[r, :take]
                            emitted[r] += take
                    t_row_h = t_row_h + counts_h.astype(np.int32)
                    done_h = np.asarray(done)
                    rounds += 1
            tok_h = np.asarray(tok)
            for r in range(b):
                # rows frozen early repeat their stop token, exactly as
                # the non-speculative fill does
                if emitted[r] < max_new:
                    out[r, int(emitted[r]):] = tok_h[r]
        rate = accepted_total / drafted if drafted else 0.0
        self.last_spec_rounds = rounds
        self.last_spec_drafted = drafted
        self.last_spec_accepted = accepted_total
        self.last_spec_acceptance = rate
        self.last_segments_run = rounds
        self.last_new_tokens_computed = int(emitted.max()) if b else 0
        # process counters surface on /metrics as _total series even with
        # no run active; the gauges ride run_summary AND the Prometheus
        # exposition (observe/export.py renders live-run gauges)
        from mmlspark_tpu.observe.metrics import inc_counter
        inc_counter("decode.spec_drafted_tokens", drafted)
        inc_counter("decode.spec_accepted_tokens", accepted_total)
        if run is not None:
            run.gauge("decode.compiled_programs", self.compiled_programs)
            run.gauge("decode.spec_acceptance_rate", round(rate, 4))
            run.gauge("decode.spec_rounds", rounds)
        return out


class TextGenerator(Transformer):
    """Pipeline Transformer: a token-prompt column in, a generated-token
    column out — the LM counterpart of TPUModel's scoring loop.

    Rows are grouped by prompt BUCKET (next power of two — a handful of
    compiled shape classes scoring full batches, the same static-shape
    discipline as vision/transformer.py's ragged grouping, now shared
    across prompt lengths) and decoded through the `DecodeEngine`:
    bucketed prefill, cache-windowed segments, stop-token early exit.
    Output rows align with input rows.  Greedy tokens are exactly those
    of per-length decoding (engine contract); sampled rows draw from a
    per-row stream keyed on (seed, row position), so a row's sample never
    depends on which rows share its table or batch.

    With `stopTokens` set, each output row is trimmed after its first
    stop token (the stop token is kept), and a batch whose rows have all
    stopped exits decode early.  `beamWidth > 0` routes through the
    full-cache per-length beam program instead (windowing lands
    sampler-first — docs/performance.md).

    MoE models: each decode step routes its batch as one capacity-limited
    group, so a row's generations can depend on which rows share its
    batch (dense models are row-independent) — see `_mlp`; bucket pad
    rows never enter the cache a real row attends, but under MoE they do
    join the step's capacity groups (the same coupling mesh zero-pad rows
    already have).
    """

    inputCol = Param(None, "column of int token-id prompt arrays",
                     ptype=str)
    outputCol = Param("generated", "output column (prompt + new tokens)",
                      ptype=str)
    maxNewTokens = Param(32, "tokens to generate per row", ptype=int,
                         validator=lambda v: v > 0)
    temperature = Param(0.0, "0 = greedy; > 0 samples with this "
                        "temperature", ptype=float,
                        validator=lambda v: v >= 0)
    topK = Param(0, "sample only among the k most probable tokens "
                 "(0 = off; ignored when greedy)", ptype=int,
                 validator=lambda v: v >= 0)
    topP = Param(1.0, "nucleus sampling: smallest probability mass to "
                 "sample within (1.0 = off; ignored when greedy)",
                 ptype=float, validator=lambda v: 0 < v <= 1)
    beamWidth = Param(0, "deterministic beam search width; each row "
                      "emits its best beam (0 = off; overrides "
                      "temperature/topK/topP; full-cache per-length "
                      "path)", ptype=int,
                      validator=lambda v: v >= 0)
    seed = Param(0, "sampling seed (ignored when greedy); each row's "
                 "stream also folds in its table position, so draws are "
                 "grouping-independent", ptype=int)
    stopTokens = Param(None, "token ids that end a row's generation: the "
                       "row is trimmed after its first stop token "
                       "(kept), and a batch whose rows have all stopped "
                       "exits decode early (None/empty = off; ignored "
                       "by beam search)", ptype=(list, tuple))
    cacheChunk = Param(DEFAULT_CACHE_CHUNK, "decode cache-window growth "
                       "granularity in slots: each compiled decode "
                       "segment attends only over the cache prefix "
                       "rounded up to this, so steady-step cost scales "
                       "with occupancy, not max_len", ptype=int,
                       validator=lambda v: v >= 1)
    kvCacheDtype = Param(None, "decode KV-cache storage dtype: 'int8' "
                         "stores the cache quantized per-head "
                         "(quantize-on-write; dequant inside the "
                         "attention read) so the steady step streams 1 "
                         "byte per cached element; None/'model' keeps "
                         "the module's own dtype.  Beam search ignores "
                         "this (full-cache model-dtype path)", ptype=str,
                         domain=("model", "int8"))
    minNewTokens = Param(1, "suppress stop tokens until a row has "
                         "generated this many tokens (including the "
                         "stop itself).  Until the floor is reachable "
                         "the engine also skips the between-segment "
                         "device->host early-exit syncs entirely "
                         "(decode.early_exit_checks_skipped gauge)",
                         ptype=int, validator=lambda v: v >= 1)
    specTokens = Param(0, "speculative decoding: tokens the draft model "
                       "proposes per verify round (0 = off; requires "
                       "set_draft_bundle).  Greedy outputs stay "
                       "byte-identical to non-speculative decoding; "
                       "sampled outputs draw from the same target "
                       "distribution via rejection sampling (different "
                       "RNG streams).  Acceptance rate lands on the "
                       "decode.spec_acceptance_rate gauge", ptype=int,
                       validator=lambda v: v >= 0)
    prefillChunk = Param(0, "chunked prefill: run prompt forwards in "
                         "chunks of this many tokens (0 = whole-prompt)."
                         "  Primarily a serving knob — serve/engine.py "
                         "interleaves chunks with resident decode "
                         "segments so long prompts don't stall running "
                         "requests; the batch path runs the same "
                         "programs", ptype=int,
                         validator=lambda v: v >= 0)

    def __init__(self, bundle: Optional["ModelBundle"] = None, **kwargs):
        super().__init__(**kwargs)
        self._bundle = bundle
        self._draft_bundle = None
        self._compiled: dict = {}
        self._mesh = None
        self._device_vars: dict = {}   # per-mesh replicated weights
        self._draft_device_vars: dict = {}

    def set_bundle(self, bundle: "ModelBundle") -> "TextGenerator":
        self._bundle = bundle
        self._compiled.clear()
        return self

    def set_draft_bundle(self, bundle) -> "TextGenerator":
        """The small LM `specTokens` speculation drafts with
        (zoo/speculative.py builds one from a target bundle).  Not
        persisted by save(): re-attach after load, exactly like a mesh."""
        self._draft_bundle = bundle
        self._compiled.clear()
        self._draft_device_vars = {}
        return self

    def set_mesh(self, mesh) -> "TextGenerator":
        """Generate data-parallel over a device mesh: prompt batches are
        sharded along the 'data' axis (zero-padded to whole shards via
        pad_to_multiple — the TPUModel batching discipline) and weights
        are placed once per mesh — replicated at mp=1, partition-rule
        sharded (heads/hidden on 'model', parallel/partition.py) when the
        mesh carries a model axis, with the KV cache following on its
        heads axis.  Dense decode is purely batch-
        parallel (no collectives in the scan; meshed output equals
        single-device output, test-pinned).  MoE decode routes each step
        cross-batch, so its dispatch spans the mesh AND the zero-pad
        rows join the capacity groups — one more instance of the MoE
        batch-composition coupling documented on this class."""
        self._mesh = mesh
        self._compiled.clear()
        self._device_vars = {}
        return self

    @property
    def bundle(self) -> Optional["ModelBundle"]:
        return self._bundle

    def _beam_fn_for(self, prompt_len: int):
        key = ("beam", prompt_len, self.maxNewTokens, self.beamWidth)
        if key not in self._compiled:
            beam_fn = make_beam_search_fn(
                self._bundle.module(), prompt_len, self.maxNewTokens,
                self.beamWidth)
            # the stage emits each row's BEST beam
            self._compiled[key] = lambda v, p, fn=beam_fn: fn(v, p)[0][:, 0]
        return self._compiled[key]

    def _engine_for(self) -> DecodeEngine:
        # greedy ignores the filters: normalize them out of the cache key
        # so flipping topK/topP at temperature 0 never rebuilds the engine
        sampling = self.temperature > 0
        top_k = (self.topK or None) if sampling else None
        top_p = self.topP if sampling and self.topP < 1.0 else None
        stops = tuple(int(t) for t in (self.stopTokens or ()))
        kv_dtype = self.kvCacheDtype or "model"
        spec = int(self.specTokens)
        if spec and self._draft_bundle is None:
            raise ValueError(
                "specTokens > 0 needs a draft model; call "
                "set_draft_bundle() (zoo/speculative.py builds one)")
        key = ("engine", self.maxNewTokens, self.temperature, top_k, top_p,
               stops, self.cacheChunk, kv_dtype, self.minNewTokens,
               self.prefillChunk or None, spec)
        if key not in self._compiled:
            self._compiled[key] = DecodeEngine(
                self._bundle.module(), self.maxNewTokens,
                temperature=self.temperature, top_k=top_k, top_p=top_p,
                stop_tokens=stops, chunk=self.cacheChunk,
                cache_dtype=kv_dtype, mesh=self._mesh,
                min_new_tokens=self.minNewTokens,
                prefill_chunk=self.prefillChunk or None,
                draft_module=(self._draft_bundle.module() if spec
                              else None),
                spec_tokens=spec)
        return self._compiled[key]

    def _device_variables(self):
        """Weights placed once per mesh (the TPUModel discipline):
        replicated on a dp-only mesh, partition-rule sharded when the
        mesh has a model axis (the bundle's own rule set when it carries
        one, DEFAULT_RULES otherwise)."""
        if self._mesh is None:
            return self._bundle.variables
        if self._mesh not in self._device_vars:
            if self._mesh.shape.get("model", 1) > 1:
                from mmlspark_tpu.parallel.partition import (
                    UNMATCHED_REPLICATE, shard_tree)
                self._device_vars[self._mesh] = shard_tree(
                    self._bundle.variables, self._mesh,
                    self._bundle.partition_rules(),
                    on_unmatched=UNMATCHED_REPLICATE)
            else:
                from mmlspark_tpu.parallel.bridge import replicate_tree
                self._device_vars[self._mesh] = replicate_tree(
                    self._bundle.variables, self._mesh)
        return self._device_vars[self._mesh]

    def _draft_device_variables(self):
        """Draft weights always replicate (the draft is small by design;
        its cache rides the data axis only — DRAFT_KV_CACHE_SPEC)."""
        if self._mesh is None:
            return self._draft_bundle.variables
        if self._mesh not in self._draft_device_vars:
            from mmlspark_tpu.parallel.bridge import replicate_tree
            self._draft_device_vars[self._mesh] = replicate_tree(
                self._draft_bundle.variables, self._mesh)
        return self._draft_device_vars[self._mesh]

    def _transform_beam(self, rows: list, out: list) -> None:
        """Beam rows decode through the full-cache per-length programs."""
        by_len: dict[int, list[int]] = {}
        for i, r in enumerate(rows):
            by_len.setdefault(len(r), []).append(i)
        for plen, idxs in sorted(by_len.items()):
            fn = self._beam_fn_for(plen)
            prompts = np.stack([rows[i] for i in idxs])
            variables = self._device_variables()
            if self._mesh is not None:
                from mmlspark_tpu.parallel.bridge import (pad_to_multiple,
                                                          put_sharded)
                from mmlspark_tpu.parallel.mesh import batch_sharding
                data = self._mesh.shape["data"]
                prompts, _ = pad_to_multiple(prompts, data)
                # one straight-to-sharded transfer (no default-device hop)
                prompts = put_sharded(prompts, batch_sharding(self._mesh))
            else:
                prompts = jnp.asarray(prompts)
            with use_mesh(self._mesh):
                got = np.asarray(fn(variables, prompts))
            for j, i in enumerate(idxs):
                out[i] = got[j]

    def _transform_engine(self, rows: list, out: list) -> None:
        """Sampler/greedy rows decode through the bucketed engine."""
        engine = self._engine_for()
        n = len(rows)
        by_bucket: dict[int, list[int]] = {}
        for i, r in enumerate(rows):
            by_bucket.setdefault(engine.bucket_for(len(r)), []).append(i)
        base = jax.random.key(self.seed)
        stops = np.asarray(engine.stop_tokens, np.int32)
        for bucket, idxs in sorted(by_bucket.items()):
            b = len(idxs)
            prompts = np.zeros((b, bucket), np.int32)
            true_len = np.empty(b, np.int32)
            for j, i in enumerate(idxs):
                true_len[j] = len(rows[i])
                prompts[j, :true_len[j]] = rows[i]
            live = np.ones(b, bool)
            # the per-row sampling-stream id is the row's TABLE position:
            # stable under any grouping or batch composition
            row_ids = np.asarray(idxs, np.int32)
            variables = self._device_variables()
            if self._mesh is not None:
                from mmlspark_tpu.parallel.bridge import put_batch_parts
                data = self._mesh.shape["data"]
                pad = -(-b // data) * data - b
                if pad:
                    prompts = np.pad(prompts, ((0, pad), (0, 0)))
                    # pad rows: length-1 zero prompts, born not-live (the
                    # engine marks them done so they never hold the batch
                    # open), unique stream ids past the real rows
                    true_len = np.pad(true_len, (0, pad), constant_values=1)
                    live = np.pad(live, (0, pad))
                    row_ids = np.concatenate(
                        [row_ids, n + np.arange(pad, dtype=np.int32)])
                prompts, true_len, live = put_batch_parts(
                    self._mesh, prompts, true_len, live)
            draft_vars = (self._draft_device_variables()
                          if engine.spec_tokens else None)
            got = engine.generate(variables, prompts, true_len, rng=base,
                                  row_ids=row_ids, live=live,
                                  draft_variables=draft_vars)
            for j, i in enumerate(idxs):
                gen = got[j]
                if stops.size:
                    # stops before the minNewTokens floor were suppressed
                    # by the engine; don't trim at them either
                    start = max(int(self.minNewTokens) - 1, 0)
                    hits = np.isin(gen[start:], stops).nonzero()[0]
                    if hits.size:
                        gen = gen[:start + hits[0] + 1]
                out[i] = np.concatenate([rows[i], gen])

    def transform(self, table: "DataTable") -> "DataTable":
        self._check_required()
        if self._bundle is None:
            raise ValueError(
                "TextGenerator has no model bundle; call set_bundle()")
        col = table[self.inputCol]
        rows = [np.asarray(r, np.int32) for r in col]
        n = len(rows)
        out: list = [None] * n
        with trace_span("generate.transform", cat="phase", rows=n,
                        beam=self.beamWidth > 0):
            if self.beamWidth > 0:
                self._transform_beam(rows, out)
            else:
                self._transform_engine(rows, out)
        if n and len({len(r) for r in out}) == 1:
            return table.with_column(self.outputCol, np.stack(out))
        result = np.empty(n, object)
        for i, r in enumerate(out):
            result[i] = r
        return table.with_column(self.outputCol, result)

    def _save_extra(self, path: str) -> None:
        if self._bundle is not None:
            save_bundle(self._bundle, f"{path}/bundle")

    def _load_extra(self, path: str) -> None:
        import os
        self._bundle = (load_bundle(f"{path}/bundle")
                        if os.path.exists(f"{path}/bundle") else None)
        self._compiled = {}
        self._mesh = None
        self._device_vars = {}
        self._draft_bundle = None
        self._draft_device_vars = {}


def naive_generate(module, variables, prompts, max_new_tokens: int) -> np.ndarray:
    """Recompute-everything greedy decoding through the ordinary module
    forward — O(N * S^2) work, no cache.  The parity oracle for
    `generate`; never the product path."""
    _check_generatable(module)
    toks = jnp.asarray(prompts, jnp.int32)
    for _ in range(max_new_tokens):
        logits = module.apply(variables, toks)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return np.asarray(toks)
