from mmlspark_tpu.models.definitions import (
    MODEL_REGISTRY,
    ConvNetCIFAR10,
    LinearModel,
    MLPClassifier,
    ResNet,
    build_model,
)
from mmlspark_tpu.models.bundle import ModelBundle, load_bundle, save_bundle
from mmlspark_tpu.models.generate import (DecodeEngine, TextGenerator,
                                          beam_search, generate,
                                          make_beam_search_fn,
                                          make_generate_fn, naive_generate)
from mmlspark_tpu.models.tpu_model import TPUModel
