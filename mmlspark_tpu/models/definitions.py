"""Model definitions: flax modules with named nodes.

Replaces the reference's CNTK computation graphs (`.model` files loaded via
JNI, CNTKModel.scala:122-132).  CNTK models expose named nodes — the
reference selects outputs by `outputNodeName`/`outputNodeIndex`
(CNTKModel.scala:151-168) and ImageFeaturizer cuts layers by `layerNames`
(ImageFeaturizer.scala:98-103).  Here every module `sow`s its named
intermediate activations, so any node is addressable without re-defining the
network: the TPU-native equivalent of CNTK's graph-node lookup, resolved at
trace time with zero runtime cost (XLA dead-code-eliminates unused heads).

All matmul/conv compute defaults to bfloat16 on the MXU with float32
parameters (the standard TPU mixed-precision recipe); pass dtype=float32 for
exact-parity runs.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class NodeMixin:
    """Helper for recording named nodes (CNTK graph-node equivalent)."""

    def node(self, name: str, value: jax.Array) -> jax.Array:
        self.sow("intermediates", name, value)
        return value


class MLPClassifier(nn.Module, NodeMixin):
    """Multi-layer perceptron (reference MLP learner, TrainClassifier.scala:96-101,
    with input-layer autosizing done by the caller as at lines 143-150)."""

    hidden_sizes: Sequence[int] = (100,)
    num_classes: int = 2
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for i, h in enumerate(self.hidden_sizes):
            x = nn.Dense(h, dtype=self.dtype, name=f"dense{i}")(x)
            x = self.node(f"h{i}", nn.relu(x))
        z = nn.Dense(self.num_classes, dtype=self.dtype, name="out")(x)
        return self.node("z", z.astype(jnp.float32))


class LinearModel(nn.Module, NodeMixin):
    """Linear/logistic model head (LR learners in TrainClassifier/Regressor)."""

    num_outputs: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        z = nn.Dense(self.num_outputs, dtype=self.dtype, name="out")(
            x.astype(self.dtype))
        return self.node("z", z.astype(jnp.float32))


class ConvNetCIFAR10(nn.Module, NodeMixin):
    """The flagship scoring model: CIFAR-10 ConvNet.

    Mirrors the capability of the reference's bundled ConvNet_CIFAR10.model
    fixture (cntk-model tests, CNTKTestUtils.scala:12-36): 3 conv blocks +
    2 dense layers over 32x32x3 images, 10-class logits at node "z".
    Named nodes: conv1..conv3, pool1..pool3, dense1, z.
    """

    num_classes: int = 10
    widths: Sequence[int] = (64, 128, 256)
    dense_width: int = 512
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # x: (B, H, W, C) float in [0, 255] or [0,1]; NHWC is XLA's preferred
        # conv layout on TPU.
        x = x.astype(self.dtype)
        for i, w in enumerate(self.widths, start=1):
            x = nn.Conv(w, (3, 3), padding="SAME", dtype=self.dtype,
                        name=f"conv{i}_w")(x)
            x = self.node(f"conv{i}", nn.relu(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = self.node(f"pool{i}", x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.dense_width, dtype=self.dtype, name="dense1_w")(x)
        x = self.node("dense1", nn.relu(x))
        z = nn.Dense(self.num_classes, dtype=self.dtype, name="out")(x)
        return self.node("z", z.astype(jnp.float32))


class ResNetBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = nn.BatchNorm(use_running_average=not train,
                                    dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class ResNetBottleneckBlock(nn.Module):
    """1x1 reduce -> 3x3 -> 1x1 expand (x4), the ResNet-50/101/152 block."""

    filters: int                       # bottleneck width; output is 4x this
    strides: tuple[int, int] = (1, 1)
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(4 * self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(4 * self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = nn.BatchNorm(use_running_average=not train,
                                    dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module, NodeMixin):
    """ResNet image featurizer (the zoo's ResNet50-class models,
    ImageFeaturizerSuite.scala:45-53 asserts a 1000-wide output).

    block_kind 'basic' gives the 18/34 layouts; 'bottleneck' the 50/101/152
    layouts (widths are the bottleneck widths; stage outputs are 4x).
    Named nodes: stem, stage1..stageN, pool (global average — the transfer-
    learning feature layer), z (classifier logits).
    """

    stage_sizes: Sequence[int] = (2, 2, 2, 2)  # ResNet-18 layout
    widths: Sequence[int] = (64, 128, 256, 512)
    num_classes: int = 1000
    block_kind: str = "basic"          # basic | bottleneck
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        block_cls = {"basic": ResNetBlock,
                     "bottleneck": ResNetBottleneckBlock}[self.block_kind]
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        x = self.node("stem", nn.relu(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, (n_blocks, w) in enumerate(zip(self.stage_sizes, self.widths), 1):
            for b in range(n_blocks):
                strides = (2, 2) if b == 0 and i > 1 else (1, 1)
                x = block_cls(w, strides, dtype=self.dtype)(x, train)
            x = self.node(f"stage{i}", x)
        x = jnp.mean(x, axis=(1, 2))
        x = self.node("pool", x.astype(jnp.float32))
        z = nn.Dense(self.num_classes, dtype=self.dtype, name="out")(x)
        return self.node("z", z.astype(jnp.float32))


def resnet50(num_classes: int = 1000, dtype: Dtype = jnp.bfloat16) -> "ResNet":
    """The canonical ResNet-50 (the reference zoo's headline featurizer,
    ModelDownloader CDN models; pool node is 2048-dim)."""
    return ResNet(stage_sizes=(3, 4, 6, 3), widths=(64, 128, 256, 512),
                  num_classes=num_classes, block_kind="bottleneck",
                  dtype=dtype)


class TransformerBlock(nn.Module):
    """Pre-norm decoder block with pluggable attention execution."""

    d_model: int
    n_heads: int
    mlp_ratio: int = 4
    dtype: Dtype = jnp.bfloat16
    attn_impl: str = "dense"    # dense | flash | ring | ring_flash | ulysses
    seq_axis: Optional[str] = None    # mesh axis for ring variants/ulysses
    mlp_impl: str = "dense"           # dense | moe
    n_experts: int = 8                # experts when mlp_impl == "moe"
    expert_axis: Optional[str] = None  # mesh axis experts shard over (EP)
    moe_router_k: int = 1             # 1 = Switch top-1, 2 = GShard top-2
    moe_group_size: int = 512         # routing group (bounds dispatch memory)

    @nn.compact
    def __call__(self, x):
        from mmlspark_tpu.ops.attention import (attention, ring_attention,
                                                ring_flash_attention,
                                                ulysses_attention)
        from mmlspark_tpu.parallel.partition import (HEADS_SPEC, HIDDEN_SPEC,
                                                     shard_constraint)
        b, s, _ = x.shape
        d_head = self.d_model // self.n_heads
        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.d_model, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (b, s, self.n_heads, d_head)
        # tensor-parallel hint (no-op off-mesh): heads ride the 'model'
        # axis, matching the column-parallel qkv kernel split — each chip
        # attends over its own head slice.  Sequence-sharded variants run
        # under shard_map, where GSPMD hints do not apply (manual axes).
        seq_sharded = self.seq_axis is not None and self.attn_impl != "dense"
        def heads(t):
            return t if seq_sharded else shard_constraint(t, HEADS_SPEC)
        q, k, v = (heads(t.reshape(shape)) for t in (q, k, v))
        if self.attn_impl == "dense":
            o = attention(q, k, v, causal=True)
        elif self.attn_impl == "flash":
            # import inside the branch: pallas is a slow import that
            # dense/ring users must not pay
            from mmlspark_tpu.ops.flash_attention import flash_attention
            o = flash_attention(q, k, v, causal=True)
        elif self.attn_impl == "ring":
            o = ring_attention(q, k, v, axis_name=self.seq_axis, causal=True)
        elif self.attn_impl == "ring_flash":
            # flash local op + LSE ring merge, differentiable (custom VJP):
            # the long-context TRAINING configuration
            o = ring_flash_attention(q, k, v, axis_name=self.seq_axis,
                                     causal=True)
        elif self.attn_impl == "ulysses":
            o = ulysses_attention(q, k, v, axis_name=self.seq_axis,
                                  causal=True)
        else:
            raise ValueError(f"unknown attn_impl '{self.attn_impl}'")
        # named for selective rematerialization: remat_policy
        # 'save_attention' stores this tensor so the backward never re-runs
        # the attention op (the flash backward already recomputes its own
        # P = exp(S - LSE) internally — re-running the forward kernel on
        # top of that is pure waste)
        from jax.ad_checkpoint import checkpoint_name
        o = checkpoint_name(heads(o), "attn_out")
        x = x + nn.Dense(self.d_model, dtype=self.dtype,
                         name="proj")(o.reshape(b, s, self.d_model))
        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.mlp_impl == "moe":
            # sparse conditional compute: Switch/GShard experts
            # (ops/moe.py); the expert dimension shards over
            # `expert_axis` via expert_parallel_rules (GSPMD EP).
            # models/generate.py::_mlp mirrors this construction for
            # KV-cache decode — keep the two in sync
            from mmlspark_tpu.ops.moe import MoEMLP
            return x + MoEMLP(self.d_model, n_experts=self.n_experts,
                              mlp_ratio=self.mlp_ratio, dtype=self.dtype,
                              expert_axis=self.expert_axis,
                              router_k=self.moe_router_k,
                              group_size=self.moe_group_size,
                              name="moe")(h)
        h = nn.Dense(self.mlp_ratio * self.d_model, dtype=self.dtype,
                     name="mlp_up")(h)
        # the hidden slice rides 'model' with the column-parallel mlp_up
        # kernel; mlp_down (row-parallel) contracts it back with one psum
        if not seq_sharded:
            h = shard_constraint(h, HIDDEN_SPEC)
        h = nn.gelu(h)
        return x + nn.Dense(self.d_model, dtype=self.dtype,
                            name="mlp_down")(h)


class TransformerLM(nn.Module, NodeMixin):
    """Decoder-only language model — the long-context flagship.

    New-design headroom over the reference (which has no sequence axis,
    SURVEY §5): with attn_impl='ring'/'ring_flash'/'ulysses' and seq_axis
    set, the model
    runs under shard_map with its sequence sharded over the mesh
    (parallel/ring.py), and position embeddings use GLOBAL positions
    derived from the device's ring index.  Named nodes: embed, block0..N,
    final_norm, z.
    """

    vocab_size: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    max_len: int = 2048
    mlp_ratio: int = 4
    dtype: Dtype = jnp.bfloat16
    attn_impl: str = "dense"
    seq_axis: Optional[str] = None
    mlp_impl: str = "dense"            # dense | moe (Switch/GShard experts)
    n_experts: int = 8
    expert_axis: Optional[str] = None  # mesh axis for expert parallelism
    moe_router_k: int = 1              # top-k routing (1=Switch, 2=GShard)
    moe_group_size: int = 512          # routing group size (memory bound)
    remat: bool = False  # rematerialize each block's activations in the
    # backward (jax.checkpoint): trades ~1 extra forward of FLOPs for
    # O(n_layers) less activation HBM — the long-context training lever
    remat_policy: str = "full"  # full | save_attention: 'save_attention'
    # stores each block's attention output (+ the flash kernel's out/lse
    # residuals) so the backward recomputes only the cheap dense ops, not
    # the attention kernel itself — costs O(B*S*D) extra HBM per layer,
    # nothing O(S^2)

    @nn.compact
    def __call__(self, tokens):
        # tokens: (B, S_local) int — S_local == S unless sequence-sharded
        s_local = tokens.shape[1]
        if self.seq_axis is not None and self.attn_impl != "dense":
            offset = jax.lax.axis_index(self.seq_axis) * s_local
        else:
            offset = 0
        pos = offset + jnp.arange(s_local)
        tok_emb = nn.Embed(self.vocab_size, self.d_model,
                           dtype=self.dtype, name="tok_embed")(tokens)
        pos_emb = nn.Embed(self.max_len, self.d_model,
                           dtype=self.dtype, name="pos_embed")(pos)
        x = self.node("embed", tok_emb + pos_emb[None])
        if not self.remat:
            block_cls = TransformerBlock
        elif self.remat_policy == "save_attention":
            block_cls = nn.remat(
                TransformerBlock,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "attn_out", "flash_out", "flash_lse"))
        elif self.remat_policy == "full":
            block_cls = nn.remat(TransformerBlock)
        else:
            raise ValueError(
                f"unknown remat_policy '{self.remat_policy}' "
                "(full | save_attention)")
        for i in range(self.n_layers):
            x = block_cls(
                self.d_model, self.n_heads, self.mlp_ratio, self.dtype,
                self.attn_impl, self.seq_axis, self.mlp_impl,
                self.n_experts, self.expert_axis, self.moe_router_k,
                self.moe_group_size, name=f"block{i}_w")(x)
            x = self.node(f"block{i}", x)
        x = nn.LayerNorm(dtype=self.dtype, name="final_norm_w")(x)
        x = self.node("final_norm", x)
        z = nn.Dense(self.vocab_size, dtype=self.dtype, name="lm_head")(x)
        return self.node("z", z.astype(jnp.float32))


# --------------------------------------------------------------------------
# Registry — serialized bundles name their architecture; build_model
# reconstructs it (the analogue of CNTK's self-describing .model files).
# --------------------------------------------------------------------------

MODEL_REGISTRY: dict[str, Callable[..., nn.Module]] = {
    "MLPClassifier": MLPClassifier,
    "LinearModel": LinearModel,
    "ConvNetCIFAR10": ConvNetCIFAR10,
    "ResNet": ResNet,
    "TransformerLM": TransformerLM,
}


def build_model(name: str, config: Optional[dict] = None) -> nn.Module:
    if name not in MODEL_REGISTRY:
        raise KeyError(f"unknown model '{name}'; known: {sorted(MODEL_REGISTRY)}")
    cfg = dict(config or {})
    if isinstance(cfg.get("dtype"), str):
        cfg["dtype"] = jnp.dtype(cfg["dtype"]).type
    if "stage_sizes" in cfg:
        cfg["stage_sizes"] = tuple(cfg["stage_sizes"])
    for k in ("hidden_sizes", "widths"):
        if k in cfg:
            cfg[k] = tuple(cfg[k])
    return MODEL_REGISTRY[name](**cfg)


def register_model(name: str, ctor: Callable[..., nn.Module]) -> None:
    MODEL_REGISTRY[name] = ctor


def model_config(module: nn.Module) -> dict:
    """Extract the JSON-safe constructor config of a registered module."""
    cfg = {}
    for field in module.__dataclass_fields__:
        if field in ("parent", "name"):
            continue
        v = getattr(module, field)
        if isinstance(v, tuple):
            v = list(v)
        elif not isinstance(v, (int, float, str, bool, type(None))):
            v = jnp.dtype(v).name  # a dtype-like (the only non-scalar field kind)
        cfg[field] = v
    return cfg
