"""ModelBundle: the serialized-model format (CNTK `.model` file replacement).

The reference ships opaque CNTK graph files loaded through JNI
(CNTKModel.scala:122-132) and even smuggles model bytes through a base64
string param (CNTKModel.scala:143-149).  Here a model is a self-describing
directory:

    bundle.json      {"architecture": <registry name>, "config": {...},
                      "metadata": {...}}
    params.msgpack   flax-serialized variables (params + batch_stats ...)

sha256 integrity is handled by the zoo layer (zoo/downloader.py), matching
the reference's Schema.scala:35-41.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import flax.linen as nn
import jax
import numpy as np
from flax import serialization

from mmlspark_tpu.models.definitions import (
    MODEL_REGISTRY,
    build_model,
    model_config,
)


def registry_name(module: nn.Module) -> str:
    """Registry key for a module — may differ from the class name when the
    model was registered via register_model under a custom key."""
    cls = type(module)
    name = cls.__name__
    if MODEL_REGISTRY.get(name) is cls:
        return name
    for k, v in MODEL_REGISTRY.items():
        if v is cls:
            return k
    raise KeyError(
        f"model class {name} is not registered; call register_model first")


@dataclasses.dataclass
class ModelBundle:
    """An architecture + its variables, ready to apply or fine-tune."""

    architecture: str
    config: dict
    variables: dict            # {"params": ..., possibly "batch_stats": ...}
    metadata: dict = dataclasses.field(default_factory=dict)

    def module(self) -> nn.Module:
        return build_model(self.architecture, self.config)

    def partition_rules(self) -> Optional[tuple]:
        """The partition-rule set this bundle was trained under (carried
        as JSON in metadata["partition"]["rules"], the
        parallel/partition.py round-trip), or None for a pre-partition
        bundle — consumers then fall back to DEFAULT_RULES."""
        data = (self.metadata or {}).get("partition", {}).get("rules")
        if not data:
            return None
        from mmlspark_tpu.parallel.partition import rules_from_json
        return rules_from_json(data)

    def partition_mesh_shape(self) -> Optional[dict]:
        """{"data": dp, "model": mp} the bundle was produced under, or
        None; arrays are always full-shape, so this is advisory (error
        messages, bench provenance) — any topology can load the bundle."""
        shape = (self.metadata or {}).get("partition", {}).get("mesh")
        return dict(shape) if shape else None

    @staticmethod
    def from_module(module: nn.Module, variables: dict,
                    metadata: Optional[dict] = None) -> "ModelBundle":
        return ModelBundle(
            architecture=registry_name(module),
            config=model_config(module),
            variables=variables,
            metadata=dict(metadata or {}),
        )

    @staticmethod
    def init(module: nn.Module, input_shape: tuple, seed: int = 0,
             metadata: Optional[dict] = None,
             input_dtype=None) -> "ModelBundle":
        """Fresh-init variables for `module` fed zeros of `input_shape`.

        The feed dtype is derived from the module when not given: token-
        input models (anything with a `vocab_size` field — their first op
        is an Embed lookup, which requires integer indices) get int32;
        everything else float32.  Pass `input_dtype` explicitly for custom
        architectures whose input convention differs.
        """
        if input_dtype is None:
            input_dtype = (np.int32
                           if getattr(module, "vocab_size", None) is not None
                           else np.float32)
        x = np.zeros(input_shape, input_dtype)
        variables = module.init(jax.random.key(seed), x)
        # unfreeze to plain dict for serialization uniformity
        variables = jax.tree_util.tree_map(np.asarray, _to_plain(variables))
        return ModelBundle.from_module(module, variables, metadata)


def _to_plain(tree):
    if hasattr(tree, "unfreeze"):
        tree = tree.unfreeze()
    if isinstance(tree, dict):
        return {k: _to_plain(v) for k, v in tree.items()}
    return tree


def _full_host_array(x) -> np.ndarray:
    """One leaf -> a full-logical-shape host array.  Model-sharded leaves
    under single-process meshes are fully addressable (np.asarray
    reassembles the shards); multi-host shards are gathered through a
    replicated identity first.  Either way what lands on disk carries the
    full shape — checkpoints stay topology-portable (restore re-commits
    onto whatever dp x mp mesh is live)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.sharding import PartitionSpec as P

        from mmlspark_tpu.parallel.partition import named_sharding
        rep = named_sharding(x.sharding.mesh, P())
        x = jax.jit(lambda t: t, out_shardings=rep)(x)
    return np.asarray(jax.device_get(x))


def save_bundle(bundle: ModelBundle, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "bundle.json"), "w") as f:
        json.dump({
            "architecture": bundle.architecture,
            "config": bundle.config,
            "metadata": bundle.metadata,
        }, f, indent=1)
    host_vars = jax.tree_util.tree_map(_full_host_array,
                                       _to_plain(bundle.variables))
    with open(os.path.join(path, "params.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(host_vars))


def load_bundle(path: str) -> ModelBundle:
    with open(os.path.join(path, "bundle.json")) as f:
        info = json.load(f)
    module = build_model(info["architecture"], info["config"])
    # Re-init with dummy shapes is avoided: from_bytes restores into a
    # None-target pytree of raw dicts/arrays.
    with open(os.path.join(path, "params.msgpack"), "rb") as f:
        variables = serialization.msgpack_restore(f.read())
    return ModelBundle(info["architecture"], info["config"], variables,
                       info.get("metadata", {}))
