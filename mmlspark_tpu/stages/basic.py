"""Column-selection, resharding and persistence stages.

TPU-native counterparts of the reference's pipeline-stages and
checkpoint-data components (SelectColumns.scala:22-63, Repartition.scala:15-42,
CheckpointData.scala:35-69).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.table import DataTable


class SelectColumns(Transformer):
    """Keep only the listed columns (reference SelectColumns.scala:22-63:
    missing columns are an error, matching Spark's analysis exception)."""

    cols = Param(None, "columns to keep", ptype=(list, tuple), required=True)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        missing = [c for c in self.cols if c not in table]
        if missing:
            raise KeyError(f"SelectColumns: no such columns {missing}; "
                           f"available: {table.columns}")
        return table.select(*self.cols)


class DropColumns(Transformer):
    """Drop the listed columns (the dual convenience stage)."""

    cols = Param(None, "columns to drop", ptype=(list, tuple), required=True)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        missing = [c for c in self.cols if c not in table]
        if missing:
            raise KeyError(f"DropColumns: no such columns {missing}; "
                           f"available: {table.columns}")
        return table.drop(*self.cols)


class RenameColumns(Transformer):
    """Rename columns via a mapping (metadata travels with the column)."""

    mapping = Param(None, "old-name -> new-name mapping", ptype=dict,
                    required=True)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        missing = [c for c in self.mapping if c not in table]
        if missing:
            raise KeyError(f"RenameColumns: no such columns {missing}")
        return table.rename(self.mapping)


class Repartition(Transformer):
    """Set the table's shard count — the layout hint the parallel layer uses
    when placing batches on the mesh.

    Reference Repartition.scala:15-42: `n` partitions with a
    `disable`/coalesce-vs-shuffle switch.  On TPU "partitions" are mesh
    shards; there is no shuffle cost distinction (resharding happens at the
    device boundary), so only the count survives.
    """

    n = Param(None, "number of shards", ptype=int, required=True,
              validator=lambda v: v > 0)
    disable = Param(False, "pass the table through unchanged", ptype=bool)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        if self.disable:
            return table
        return table.repartition(self.n)


class CheckpointData(Transformer):
    """Materialize (or release) table columns in device HBM.

    Reference CheckpointData.scala:35-69 caches/unpersists a DataFrame in
    executor memory as a pipeline stage.  The TPU equivalent of "cache" is
    pre-staging numeric columns into device memory so downstream scoring
    stages skip the host->HBM transfer on every pass over the table (e.g.
    FindBestModel scoring many models on one eval set); "unpersist"
    (removeCheckpoint=True) drops those buffers.  The cache lives on the
    table object itself, so it is garbage-collected with the table;
    TPUModel consults it via `get_device_cache`.
    """

    removeCheckpoint = Param(False, "release instead of persist", ptype=bool)

    def transform(self, table: DataTable) -> DataTable:
        from mmlspark_tpu.parallel.bridge import shard_batch
        from mmlspark_tpu.parallel.mesh import best_mesh

        out = table.select(*table.columns)
        if self.removeCheckpoint:
            # deliberate mutation of the input (the one exception to the
            # derived-table convention): any holder of the input table keeps
            # HBM pinned through its _device_cache, so drop it there too
            table.__dict__.pop("_device_cache", None)
            return out
        cache: dict[str, object] = {}
        # stage with the mesh BATCH sharding (not default single-device
        # placement): TPUModel slices this cache per minibatch, and a
        # default-placed column would silently reshard — a cross-device
        # gather — on every batch.  With batch sharding the per-batch
        # reshard is a no-op on the default mesh.  shard_batch pads rows
        # to a data-axis multiple; consumers take valid counts from the
        # HOST column length (the cache is layout, not truth).
        mesh = best_mesh()
        for name in out.columns:
            arr = out[name]
            if arr.dtype != object and np.issubdtype(arr.dtype, np.number):
                cache[name] = shard_batch(np.ascontiguousarray(arr), mesh)
        out.__dict__["_device_cache"] = cache
        return out

    @staticmethod
    def get_device_cache(table: DataTable) -> dict[str, object]:
        return getattr(table, "_device_cache", {})
