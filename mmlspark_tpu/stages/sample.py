"""Head / random-sample / assign-to-partition stage.

TPU-native counterpart of the reference's PartitionSample
(partition-sample/PartitionSample.scala:87-110).  The reference's
AssignToPartition mode was broken (line 92 copies an "input" column); here it
does what its params describe: assigns each row a random shard id in
[0, numParts) into `newColName`.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import Param, domain
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.table import DataTable

MODE_RS = "RandomSample"
MODE_HEAD = "Head"
MODE_ATP = "AssignToPartition"
RS_ABSOLUTE = "Absolute"
RS_PERCENT = "Percentage"


class PartitionSample(Transformer):
    """Sample rows or assign partition ids."""

    mode = Param(MODE_RS, "sampling mode",
                 domain=domain(MODE_RS, MODE_HEAD, MODE_ATP))
    rsMode = Param(RS_PERCENT, "random-sample mode",
                   domain=domain(RS_ABSOLUTE, RS_PERCENT))
    seed = Param(-1, "seed for random ops (-1 = nondeterministic)", ptype=int)
    percent = Param(0.01, "fraction of rows to return", ptype=float)
    count = Param(1000, "number of rows to return", ptype=int)
    newColName = Param("Partition", "partition column name (ATP mode)", ptype=str)
    numParts = Param(10, "number of partitions (ATP mode)", ptype=int)

    def _rng(self) -> np.random.Generator:
        seed = self.seed
        return np.random.default_rng(None if seed == -1 else seed)

    def transform(self, table: DataTable) -> DataTable:
        mode = self.mode
        if mode == MODE_HEAD:
            return table.take(self.count)
        if mode == MODE_RS:
            frac = (self.percent if self.rsMode == RS_PERCENT
                    else min(1.0, self.count / max(1, table.num_rows)))
            mask = self._rng().random(table.num_rows) < frac
            return table.filter(mask)
        parts = self._rng().integers(0, self.numParts,
                                     size=table.num_rows).astype(np.int32)
        return table.with_column(self.newColName, parts)
