"""Column type coercion stage.

TPU-native counterpart of the reference's DataConversion
(data-conversion/DataConversion.scala:51-149): convert a comma-separated
list of columns to a target type, including to/from categorical and
date/timestamp handling.
"""

from __future__ import annotations

import datetime as _dt

import numpy as np

from mmlspark_tpu.core.params import Param, domain
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import make_categorical
from mmlspark_tpu.core.table import DataTable

_NUMERIC_TARGETS = {
    "boolean": np.bool_,
    "byte": np.int8,
    "short": np.int16,
    "integer": np.int32,
    "long": np.int64,
    "float": np.float32,
    "double": np.float64,
}


class DataConversion(Transformer):
    """Convert listed columns to a requested type.

    `cols` accepts a list or the reference's comma-separated string form
    (DataConversion.scala:25-26, 55).  Targets mirror the reference's
    dispatch at lines 65-78: numeric types, string, toCategorical,
    clearCategorical, date.
    """

    cols = Param(None, "columns to convert (list or comma-separated string)",
                 required=True)
    convertTo = Param(None, "target type", ptype=str, required=True,
                      domain=domain("boolean", "byte", "short", "integer",
                                    "long", "float", "double", "string",
                                    "toCategorical", "clearCategorical",
                                    "date"))
    dateTimeFormat = Param("%Y-%m-%d %H:%M:%S",
                           "strptime/strftime format for date conversions "
                           "(reference default yyyy-MM-dd HH:mm:ss)",
                           ptype=str)

    def _col_list(self) -> list[str]:
        cols = self.cols
        if isinstance(cols, str):
            return [c.strip() for c in cols.split(",") if c.strip()]
        return list(cols)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        names = self._col_list()
        missing = [c for c in names if c not in table]
        if missing:
            raise KeyError(f"DataConversion: no such columns {missing}")
        out = table
        for name in names:
            out = self._convert(out, name)
        return out

    def _convert(self, table: DataTable, name: str) -> DataTable:
        target = self.convertTo
        arr = table[name]
        if target == "toCategorical":
            return make_categorical(table, name)
        if target == "clearCategorical":
            cmap = table.meta(name).categorical
            if cmap is None:
                return table
            decoded = cmap.to_levels(arr)
            out = table.with_column(name, decoded)
            meta = out.meta(name)
            meta.categorical = None
            out.set_meta(name, meta)
            return out
        if target == "date":
            return table.with_column(name, self._to_datetime(arr))
        if target == "string":
            if np.issubdtype(arr.dtype, np.datetime64):
                return table.with_column(name, self._format_dates(arr))
            str_col = np.empty(len(arr), dtype=object)
            str_col[:] = [str(v) for v in arr]
            return table.with_column(name, str_col)
        np_target = _NUMERIC_TARGETS[target]
        if arr.dtype == object and np_target is np.bool_:
            # reference rejects string->boolean (DataConversion.scala:108)
            if any(isinstance(v, str) for v in arr):
                raise TypeError("string to boolean conversion not supported")
        if np.issubdtype(arr.dtype, np.datetime64):
            # timestamp -> long (epoch millis) or string only
            # (DataConversion.scala:117-126)
            if np_target is not np.int64:
                raise TypeError("date columns only convert to long or string")
            millis = arr.astype("datetime64[ms]").astype(np.int64)
            return table.with_column(name, millis)
        if arr.dtype == object:
            integral = np_target is not np.bool_ and np.issubdtype(
                np_target, np.integer)

            def conv(v):
                if integral:
                    # never round-trip large ints through float64 (2**53 loss)
                    return int(v) if not isinstance(v, str) else int(
                        v) if v.lstrip("+-").isdigit() else int(float(v))
                return float(v) if not isinstance(v, str) else np.float64(v)

            converted = np.asarray([conv(v) for v in arr], dtype=np_target)
            return table.with_column(name, converted)
        return table.with_column(name, arr.astype(np_target))

    def _to_datetime(self, arr: np.ndarray) -> np.ndarray:
        fmt = self.dateTimeFormat
        if np.issubdtype(arr.dtype, np.datetime64):
            return arr
        if np.issubdtype(arr.dtype, np.integer):
            # epoch millis -> datetime64[ms] (reference long->Timestamp path)
            return arr.astype("datetime64[ms]")
        parsed = [np.datetime64(_dt.datetime.strptime(str(v), fmt), "ms")
                  for v in arr]
        return np.asarray(parsed, dtype="datetime64[ms]")

    def _format_dates(self, arr: np.ndarray) -> np.ndarray:
        fmt = self.dateTimeFormat
        out = np.empty(len(arr), dtype=object)
        out[:] = [v.astype("datetime64[ms]").astype(_dt.datetime).strftime(fmt)
                  for v in arr]
        return out
