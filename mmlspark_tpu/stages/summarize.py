"""Per-column statistics stage.

TPU-native counterpart of the reference's SummarizeData
(summarize-data/SummarizeData.scala:65-190): emits one row per input column
with count / basic / sample / percentile statistic groups.  Where the
reference joins four Spark jobs with approximate quantiles, the table is
host-resident here, so quantiles are exact (errorThreshold kept for API
parity; 0 == exact was already the reference default).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.table import DataTable

FEATURE_COLUMN = "Feature"
COUNT_FIELDS = ["Count", "Unique Value Count", "Missing Value Count"]
BASIC_FIELDS = ["Min", "1st Quartile", "Median", "3rd Quartile", "Max"]
BASIC_QUANTILES = [0.0, 0.25, 0.5, 0.75, 1.0]
SAMPLE_FIELDS = ["Sample Variance", "Sample Standard Deviation",
                 "Sample Skewness", "Sample Kurtosis"]
PERCENTILE_QUANTILES = [0.005, 0.01, 0.05, 0.95, 0.99, 0.995]
PERCENTILE_FIELDS = ["P0.5", "P1", "P5", "P95", "P99", "P99.5"]


class SummarizeData(Transformer):
    """Compute per-column summary statistics as a new table."""

    counts = Param(True, "compute count statistics", ptype=bool)
    basic = Param(True, "compute basic statistics (min/quartiles/max)", ptype=bool)
    sample = Param(True, "compute sample statistics (var/std/skew/kurtosis)",
                   ptype=bool)
    percentiles = Param(True, "compute tail percentiles", ptype=bool)
    errorThreshold = Param(0.0, "quantile error tolerance; 0 is exact "
                           "(always exact here)", ptype=float)

    def transform(self, table: DataTable) -> DataTable:
        names = table.columns
        out: dict[str, list] = {FEATURE_COLUMN: list(names)}
        groups: list[tuple[list[str], callable, bool]] = []
        if self.counts:
            groups.append((COUNT_FIELDS, _count_stats, False))
        if self.basic:
            groups.append((BASIC_FIELDS,
                           lambda v: _quantiles(v, BASIC_QUANTILES), True))
        if self.sample:
            groups.append((SAMPLE_FIELDS, _sample_stats, True))
        if self.percentiles:
            groups.append((PERCENTILE_FIELDS,
                           lambda v: _quantiles(v, PERCENTILE_QUANTILES), True))
        for fields, _, _ in groups:
            for f in fields:
                out[f] = []
        for name in names:
            arr = table[name]
            numeric = (arr.dtype != object and arr.ndim == 1
                       and np.issubdtype(arr.dtype, np.number))
            for fields, fn, needs_numeric in groups:
                if needs_numeric and not numeric:
                    vals = [np.nan] * len(fields)
                else:
                    vals = fn(arr)
                for f, v in zip(fields, vals):
                    out[f].append(float(v))
        return DataTable(out)


def _count_stats(arr: np.ndarray) -> list[float]:
    n = len(arr)
    if arr.dtype == object:
        missing = sum(1 for v in arr if v is None)
        present = [v for v in arr if v is not None]
        distinct = len(set(map(_hashable, present)))
    elif arr.ndim > 1:
        missing = 0
        distinct = len({v.tobytes() for v in arr})
    elif np.issubdtype(arr.dtype, np.floating):
        nan = np.isnan(arr)
        missing = int(nan.sum())
        distinct = len(np.unique(arr[~nan]))
    else:
        missing = 0
        distinct = len(np.unique(arr))
    return [n - missing, distinct, missing]


def _hashable(v):
    return v.tobytes() if isinstance(v, np.ndarray) else v


def _quantiles(arr: np.ndarray, qs: list[float]) -> list[float]:
    vals = arr[~np.isnan(arr)] if np.issubdtype(arr.dtype, np.floating) else arr
    if len(vals) == 0:
        return [np.nan] * len(qs)
    return list(np.quantile(vals.astype(np.float64), qs))


def _sample_stats(arr: np.ndarray) -> list[float]:
    vals = (arr[~np.isnan(arr)] if np.issubdtype(arr.dtype, np.floating)
            else arr).astype(np.float64)
    n = len(vals)
    if n < 2:
        return [np.nan] * 4
    var = vals.var(ddof=1)
    std = np.sqrt(var)
    # Spark's skewness/kurtosis are the population m3/m2^1.5 and excess
    # m4/m2^2 - 3 (what the reference's sampleStatsImpl computed via
    # catalyst's skewness/kurtosis aggregates)
    m = vals.mean()
    m2 = ((vals - m) ** 2).mean()
    if m2 == 0:
        return [var, std, np.nan, np.nan]
    skew = ((vals - m) ** 3).mean() / m2 ** 1.5
    kurt = ((vals - m) ** 4).mean() / m2 ** 2 - 3.0
    return [var, std, skew, kurt]
