"""Utility pipeline stages (reference L3: pipeline-stages, data-conversion,
summarize-data, partition-sample, checkpoint-data, multi-column-adapter)."""

from mmlspark_tpu.stages.basic import (CheckpointData, DropColumns,
                                       RenameColumns, Repartition,
                                       SelectColumns)
from mmlspark_tpu.stages.data_conversion import DataConversion
from mmlspark_tpu.stages.summarize import SummarizeData
from mmlspark_tpu.stages.sample import PartitionSample
from mmlspark_tpu.stages.adapter import MultiColumnAdapter

__all__ = [
    "SelectColumns", "DropColumns", "RenameColumns", "Repartition",
    "CheckpointData", "DataConversion", "SummarizeData", "PartitionSample",
    "MultiColumnAdapter",
]
