"""Apply a unary stage to N column pairs.

TPU-native counterpart of the reference's MultiColumnAdapter
(multi-column-adapter/MultiColumnAdapter.scala:73-98): takes a base stage
with inputCol/outputCol params, clones it per (input, output) pair and
chains the applications.  The reference rewired params by reflection; here
the Param protocol makes the rewiring a plain `copy(inputCol=…, outputCol=…)`.
"""

from __future__ import annotations

import os
from typing import Optional

from mmlspark_tpu.core.params import Param, ParamError
from mmlspark_tpu.core.pipeline import (Estimator, PipelineModel,
                                        PipelineStage, Transformer,
                                        load_stage)
from mmlspark_tpu.core.table import DataTable


class MultiColumnAdapter(Estimator):
    """Fit/apply `baseStage` once per (inputCol, outputCol) pair."""

    inputCols = Param(None, "input column names", ptype=(list, tuple),
                      required=True)
    outputCols = Param(None, "output column names", ptype=(list, tuple),
                       required=True)

    def __init__(self, base_stage: Optional[PipelineStage] = None, **kwargs):
        super().__init__(**kwargs)
        self._base = base_stage

    def set_base_stage(self, stage: PipelineStage) -> "MultiColumnAdapter":
        self._base = stage
        return self

    @property
    def base_stage(self) -> Optional[PipelineStage]:
        return self._base

    def _pairs(self) -> list[tuple[str, str]]:
        self._check_required()
        ins, outs = list(self.inputCols), list(self.outputCols)
        if len(ins) != len(outs):
            raise ParamError(
                f"MultiColumnAdapter: {len(ins)} input cols vs "
                f"{len(outs)} output cols")
        return list(zip(ins, outs))

    def _clone_base(self, in_col: str, out_col: str) -> PipelineStage:
        if self._base is None:
            raise ParamError("MultiColumnAdapter: base stage not set")
        for p in ("inputCol", "outputCol"):
            if not self._base.has_param(p):
                raise ParamError(
                    f"base stage {type(self._base).__name__} lacks param '{p}'")
        return self._base.copy(inputCol=in_col, outputCol=out_col)

    def fit(self, table: DataTable) -> PipelineModel:
        fitted: list[Transformer] = []
        current = table
        for in_col, out_col in self._pairs():
            stage = self._clone_base(in_col, out_col)
            model = stage.fit(current) if isinstance(stage, Estimator) else stage
            current = model.transform(current)
            fitted.append(model)
        return PipelineModel(fitted)

    def transform(self, table: DataTable) -> DataTable:
        """Convenience direct application when the base is a Transformer."""
        if isinstance(self._base, Estimator):
            raise TypeError("base stage is an Estimator; use fit()")
        current = table
        for in_col, out_col in self._pairs():
            current = self._clone_base(in_col, out_col).transform(current)
        return current

    def _save_extra(self, path: str) -> None:
        if self._base is not None:
            self._base.save(os.path.join(path, "base"))

    def _load_extra(self, path: str) -> None:
        base = os.path.join(path, "base")
        self._base = load_stage(base) if os.path.exists(base) else None
