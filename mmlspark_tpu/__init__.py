"""mmlspark_tpu — a TPU-native ML pipeline framework.

A from-scratch reimplementation of the capabilities of MMLSpark
(gdtm86/mmlspark): SparkML-style Estimator/Transformer pipelines with
metadata-carrying schemas, implicit featurization, rich evaluation, image
ingestion/processing, a pretrained-model zoo, and distributed DNN scoring and
training — designed for TPUs.  Execution is JAX/XLA: `jit`-compiled array
programs sharded over a `jax.sharding.Mesh` (ICI/DCN) replace the reference's
CNTK-JNI bridge and MPI ring; batched XLA/Pallas kernels over HBM-resident
image tensors replace per-row OpenCV JNI calls.

Layer map (mirrors SURVEY.md section 1 of the reference analysis):
  core/      - params DSL, schema metadata, pipeline kernel, table runtime
  parallel/  - device mesh, sharding, collectives, multi-host init
  ops/       - batched image/array kernels (XLA + Pallas)
  models/    - flax model definitions + TPUModel distributed scoring
  train/     - in-process distributed trainer (TPULearner)
  ml/        - featurization, auto-ML train stages, evaluation
  stages/    - utility pipeline stages
  io/        - readers (image/binary/csv) and writers
  resilience/- retry/breaker policies, chaos injection, checkpoint
               rotation, preemption handling (docs/resilience.md)
  quant/     - post-training quantization: int8/bf16 bundles, fused
               wrappers, int8 KV cache, accuracy gates (docs/performance.md)
  zoo/       - pretrained model repository client
  native/    - C++ host-side runtime pieces (decode, parse, hash)
"""

__version__ = "0.1.0"

from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.core.pipeline import (
    Estimator,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
    load_stage,
)
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.observe import (MetricData, get_logger, pipeline_timing,
                                  profile, run_telemetry, stage_timing)

# persistent XLA compilation cache (MMLSPARK_TPU_COMPILATION_CACHE): wired
# before any model compiles so warm restarts skip recompiles entirely
from mmlspark_tpu.config import setup_compilation_cache as _setup_cc

_setup_cc()
del _setup_cc
