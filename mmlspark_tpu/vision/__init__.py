"""Vision pipeline stages (reference image-transformer/, image-featurizer/)."""

from mmlspark_tpu.vision.transformer import ImageTransformer, UnrollImage
from mmlspark_tpu.vision.featurizer import ImageFeaturizer

__all__ = ["ImageTransformer", "UnrollImage", "ImageFeaturizer"]
