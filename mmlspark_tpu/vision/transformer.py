"""ImageTransformer: a compiled pipeline of batched image ops.

TPU-native counterpart of the reference's image-transformer
(ImageTransformer.scala:28-154 stage classes, 272-304 UDF application):
the same fluent stage API (resize/crop/colorFormat/blur/threshold/
gaussianKernel/flip), but instead of one OpenCV JNI call per row per
stage, the whole op list composes into ONE jitted function applied to the
batched (N, H, W, C) tensor — XLA fuses adjacent elementwise stages, so a
resize+normalize+threshold chain costs one HBM round trip.

Ragged inputs (object column of differently-sized images) are grouped by
shape; each group runs as one batched dispatch (one compile per distinct
source shape).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import ColumnMeta, ImageSchema
from mmlspark_tpu.core.table import DataTable, object_column
from mmlspark_tpu.ops import image as ops

# stage names follow the reference (ImageTransformer.scala objects)
_STAGE_FNS = {
    "resize": lambda x, p: ops.resize(x, p["height"], p["width"]),
    "crop": lambda x, p: ops.crop(x, p["x"], p["y"], p["height"], p["width"]),
    "centercrop": lambda x, p: ops.center_crop(x, p["height"], p["width"]),
    "colorformat": lambda x, p: ops.cvt_color(x, p["format"]),
    "blur": lambda x, p: ops.blur(x, int(p["height"]), int(p["width"])),
    "threshold": lambda x, p: ops.threshold(x, p["threshold"], p["maxVal"],
                                            p.get("type", "binary")),
    "gaussiankernel": lambda x, p: ops.gaussian_kernel(
        x, p["appertureSize"], p["sigma"]),
    "flip": lambda x, p: ops.flip(x, p.get("code", 1)),
    "normalize": lambda x, p: ops.normalize(x, p.get("mean"), p.get("std")),
}


class ImageTransformer(Transformer):
    """Apply a sequence of image ops to an image column."""

    inputCol = Param("image", "input image column", ptype=str)
    outputCol = Param("image", "output image column", ptype=str)
    stages = Param(None, "op list: [{'stage': name, ...params}]",
                   ptype=(list, tuple))

    # -- fluent builders (reference setter API) -------------------------
    def _add(self, stage: str, **params) -> "ImageTransformer":
        cur = list(self.stages or [])
        cur.append({"stage": stage, **params})
        return self.set("stages", cur)

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add("resize", height=height, width=width)

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add("crop", x=x, y=y, height=height, width=width)

    def center_crop(self, height: int, width: int) -> "ImageTransformer":
        return self._add("centercrop", height=height, width=width)

    def color_format(self, format: str) -> "ImageTransformer":
        return self._add("colorformat", format=format)

    def blur(self, height: float, width: float) -> "ImageTransformer":
        return self._add("blur", height=height, width=width)

    def threshold(self, threshold: float, max_val: float,
                  thresh_type: str = "binary") -> "ImageTransformer":
        return self._add("threshold", threshold=threshold, maxVal=max_val,
                         type=thresh_type)

    def gaussian_kernel(self, apperture_size: int, sigma: float) -> "ImageTransformer":
        return self._add("gaussiankernel", appertureSize=apperture_size,
                         sigma=sigma)

    def flip(self, code: int = 1) -> "ImageTransformer":
        return self._add("flip", code=code)

    def normalize(self, mean=None, std=None) -> "ImageTransformer":
        return self._add("normalize", mean=mean, std=std)

    # -- application ----------------------------------------------------
    def _apply_ops(self, batch: np.ndarray) -> np.ndarray:
        x = batch
        for spec in (self.stages or []):
            name = spec["stage"]
            if name not in _STAGE_FNS:
                raise ValueError(f"unknown image stage '{name}'; "
                                 f"known: {sorted(_STAGE_FNS)}")
            x = _STAGE_FNS[name](x, spec)
        return np.asarray(x)

    def transform(self, table: DataTable) -> DataTable:
        col = table[self.inputCol]
        if col.dtype == object:
            # ragged: group rows by shape, one batched dispatch per group
            by_shape: dict[tuple, list[int]] = {}
            for i, img in enumerate(col):
                by_shape.setdefault(np.asarray(img).shape, []).append(i)
            results: list[Optional[np.ndarray]] = [None] * len(col)
            out_shapes = set()
            for shape, idxs in by_shape.items():
                batch = np.stack([np.asarray(col[i]) for i in idxs])
                out = self._apply_ops(batch)
                out_shapes.add(out.shape[1:])
                for j, i in enumerate(idxs):
                    results[i] = out[j]
            if len(out_shapes) == 1 and results:
                stacked = np.stack(results)
                return self._with_image(table, stacked)
            return table.with_column(self.outputCol, object_column(results))
        return self._with_image(table, self._apply_ops(col))

    def _with_image(self, table: DataTable, arr: np.ndarray) -> DataTable:
        meta = ColumnMeta(image=ImageSchema(
            height=arr.shape[1], width=arr.shape[2],
            channels=arr.shape[3] if arr.ndim > 3 else 1))
        return table.with_column(self.outputCol, arr, meta=meta)


class UnrollImage(Transformer):
    """Flatten images to CHW float vectors for classical learners
    (reference UnrollImage.scala:18-42)."""

    inputCol = Param("image", "input image column", ptype=str)
    outputCol = Param("unrolled", "flattened output column", ptype=str)

    def transform(self, table: DataTable) -> DataTable:
        col = table[self.inputCol]
        if col.dtype == object:
            raise ValueError(
                "UnrollImage needs a uniform image tensor; resize first "
                "(ImageTransformer.resize)")
        flat = np.asarray(ops.unroll(col))
        return table.with_column(self.outputCol, flat)
