"""ImageFeaturizer: transfer learning from zoo models.

TPU-native counterpart of the reference's image-featurizer
(ImageFeaturizer.scala:93-120): resize the image column to the model's
input shape, run a TRUNCATED forward pass (cut `cutOutputLayers` named
layers off the head, scala:98-103), and emit the activations as features.
Where the reference rebuilt a CNTK graph via cutOutputLayers over the
ModelSchema's layerNames, here the cut resolves to a named node in the
flax module (models/definitions.py) and XLA dead-code-eliminates
everything past it — the truncation is free at compile time.
"""

from __future__ import annotations

import os
from typing import Optional


from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle, load_bundle, save_bundle
from mmlspark_tpu.models.tpu_model import TPUModel
from mmlspark_tpu.vision.transformer import ImageTransformer


class ImageFeaturizer(Transformer):
    """Truncated-model image featurization."""

    inputCol = Param("image", "image column", ptype=str)
    outputCol = Param("features", "feature output column", ptype=str)
    cutOutputLayers = Param(1, "how many named output layers to cut "
                            "(1 = use the layer feeding the classifier head, "
                            "ImageFeaturizer.scala:60-66)", ptype=int)
    layerName = Param(None, "explicit node to output (overrides "
                      "cutOutputLayers)", ptype=str)
    inputHeight = Param(None, "model input height (None = from bundle "
                        "metadata)", ptype=int)
    inputWidth = Param(None, "model input width", ptype=int)
    scaleToUnit = Param(True, "scale uint8 [0,255] to [0,1] before the net",
                        ptype=bool)
    miniBatchSize = Param(512, "scoring batch size", ptype=int)

    def __init__(self, bundle: Optional[ModelBundle] = None, **kw):
        super().__init__(**kw)
        self._bundle = bundle

    def set_bundle(self, bundle: ModelBundle) -> "ImageFeaturizer":
        self._bundle = bundle
        return self

    @property
    def bundle(self) -> Optional[ModelBundle]:
        return self._bundle

    def _resolve_layer(self) -> Optional[str]:
        if self.layerName is not None:
            return self.layerName
        layer_names = (self._bundle.metadata or {}).get("layer_names")
        cut = self.cutOutputLayers
        if layer_names:
            # layer_names ordered output-side first, as the reference's
            # ModelSchema.layerNames (Schema.scala:56-76)
            if cut >= len(layer_names):
                raise ValueError(
                    f"cutOutputLayers={cut} but model only names "
                    f"{len(layer_names)} layers: {layer_names}")
            return layer_names[cut] if cut > 0 else None
        return None  # final output

    def _input_hw(self) -> Optional[tuple[int, int]]:
        if self.inputHeight is not None and self.inputWidth is not None:
            return (self.inputHeight, self.inputWidth)
        shape = (self._bundle.metadata or {}).get("input_shape")
        if shape and len(shape) == 4:
            return (int(shape[1]), int(shape[2]))
        return None

    def transform(self, table: DataTable) -> DataTable:
        if self._bundle is None:
            raise ValueError("ImageFeaturizer has no model bundle")
        work_col = table.find_unused_column_name(f"{self.outputCol}_img")
        hw = self._input_hw()
        current = table
        it = ImageTransformer(inputCol=self.inputCol, outputCol=work_col)
        if hw is not None:
            it = it.resize(*hw)
        if self.scaleToUnit:
            it = it.normalize()
        if it.stages:
            current = it.transform(current)
            src_col = work_col
        else:
            src_col = self.inputCol

        scorer = TPUModel(self._bundle, inputCol=src_col,
                          outputCol=self.outputCol,
                          miniBatchSize=self.miniBatchSize,
                          outputNodeName=self._resolve_layer())
        out = scorer.transform(current)
        return out.drop(work_col) if work_col in out else out

    # -- persistence ----------------------------------------------------
    def _save_extra(self, path: str) -> None:
        if self._bundle is not None:
            save_bundle(self._bundle, os.path.join(path, "bundle"))

    def _load_extra(self, path: str) -> None:
        p = os.path.join(path, "bundle")
        self._bundle = load_bundle(p) if os.path.exists(p) else None
