// Fused text featurization: tokenize -> stop-filter -> hash -> count in one
// C++ sweep over raw UTF-8 document bytes.
//
// The Python stage chain (feature/text.py: Tokenizer -> StopWordsRemover ->
// HashingTF) materializes every token as a Python str; at corpus scale the
// host becomes the bottleneck while the TPU idles (the reference ran this
// as distributed JVM work, TextFeaturizer.scala:230-290).  This kernel
// replicates the DEFAULT chain semantics exactly for pure-ASCII documents:
//
//   * whitespace-split identical to Python re.split(r"\s+") on ASCII text:
//     separators are { \t \n \v \f \r space \x1c \x1d \x1e \x1f } (the
//     ASCII subset of unicode \s); empty tokens are dropped.
//   * optional ASCII lowercasing (== str.lower() for ASCII).
//   * optional stop-word removal; membership may be tested on a lowercased
//     copy (lower_for_stop) while the token itself stays unmodified, which
//     mirrors `(t if cs else t.lower()) not in stop`.
//   * zlib crc32 (== feature/hashing.py stable_hash) modulo num_features,
//     per-document sorted-unique slot counts (== np.unique semantics).
//
// Documents containing any byte >= 0x80 are NOT processed (status=1): the
// caller recomputes those rows through the Python path, because unicode
// whitespace/lowercasing tables belong in Python, not here.  One C++
// entry point per concern, C ABI, loaded via ctypes (native_loader.py).

#include <zlib.h>

#include <cstdint>

#include <algorithm>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

inline bool is_ws(unsigned char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\v' || c == '\f' ||
           c == '\r' || (c >= 0x1c && c <= 0x1f);
}

inline char ascii_lower(char c) {
    return (c >= 'A' && c <= 'Z') ? static_cast<char>(c + 32) : c;
}

}  // namespace

extern "C" {

// Returns 0 on success.  Outputs are malloc'd here and released with
// text_hash_free: slots/vals hold the concatenated per-doc sorted unique
// (slot, count) pairs, bounds is an (n_docs+1) prefix, status[i] is 1 when
// doc i contained non-ASCII bytes and was skipped (bounds stay flat there).
int text_hash_count(const char* buf, const int64_t* offsets, int64_t n_docs,
                    const char* stop_buf, const int64_t* stop_offsets,
                    int64_t n_stop, int lowercase, int lower_for_stop,
                    int64_t min_token_len, int64_t num_features, int binary,
                    int** out_slots, float** out_vals, int64_t** out_bounds,
                    unsigned char** out_status) {
    if (num_features <= 0) return 1;
    std::unordered_set<std::string> stop;
    stop.reserve(static_cast<size_t>(n_stop) * 2);
    for (int64_t i = 0; i < n_stop; ++i)
        stop.emplace(stop_buf + stop_offsets[i],
                     static_cast<size_t>(stop_offsets[i + 1] -
                                         stop_offsets[i]));

    std::vector<int> slots;
    std::vector<float> vals;
    std::vector<int64_t> bounds(1, 0);
    bounds.reserve(static_cast<size_t>(n_docs) + 1);
    unsigned char* status = static_cast<unsigned char*>(
        std::malloc(n_docs ? static_cast<size_t>(n_docs) : 1));
    if (!status) return 2;

    std::string token, lowered;
    std::vector<unsigned int> doc_slots;
    for (int64_t d = 0; d < n_docs; ++d) {
        const char* p = buf + offsets[d];
        const int64_t len = offsets[d + 1] - offsets[d];
        status[d] = 0;
        for (int64_t i = 0; i < len; ++i) {
            if (static_cast<unsigned char>(p[i]) >= 0x80) {
                status[d] = 1;  // non-ASCII: Python recomputes this row
                break;
            }
        }
        doc_slots.clear();
        if (!status[d]) {
            int64_t i = 0;
            while (i < len) {
                while (i < len && is_ws(static_cast<unsigned char>(p[i])))
                    ++i;
                int64_t start = i;
                while (i < len && !is_ws(static_cast<unsigned char>(p[i])))
                    ++i;
                const int64_t tlen = i - start;
                if (tlen == 0 || tlen < min_token_len) continue;
                token.assign(p + start, static_cast<size_t>(tlen));
                if (lowercase)
                    for (auto& c : token) c = ascii_lower(c);
                if (!stop.empty()) {
                    const std::string* probe = &token;
                    if (lower_for_stop && !lowercase) {
                        lowered = token;
                        for (auto& c : lowered) c = ascii_lower(c);
                        probe = &lowered;
                    }
                    if (stop.count(*probe)) continue;
                }
                const uLong h = crc32(
                    0L, reinterpret_cast<const Bytef*>(token.data()),
                    static_cast<uInt>(token.size()));
                doc_slots.push_back(static_cast<unsigned int>(
                    static_cast<uint64_t>(h) %
                    static_cast<uint64_t>(num_features)));
            }
            std::sort(doc_slots.begin(), doc_slots.end());
            for (size_t j = 0; j < doc_slots.size();) {
                size_t k = j;
                while (k < doc_slots.size() && doc_slots[k] == doc_slots[j])
                    ++k;
                slots.push_back(static_cast<int>(doc_slots[j]));
                vals.push_back(binary ? 1.0f
                                      : static_cast<float>(k - j));
                j = k;
            }
        }
        bounds.push_back(static_cast<int64_t>(slots.size()));
    }

    const size_t n_out = slots.size();
    int* s_out = static_cast<int*>(std::malloc(n_out ? n_out * 4 : 4));
    float* v_out = static_cast<float*>(std::malloc(n_out ? n_out * 4 : 4));
    int64_t* b_out = static_cast<int64_t*>(
        std::malloc(bounds.size() * sizeof(int64_t)));
    if (!s_out || !v_out || !b_out) {
        std::free(s_out); std::free(v_out); std::free(b_out);
        std::free(status);
        return 2;
    }
    if (n_out) {
        std::memcpy(s_out, slots.data(), n_out * 4);
        std::memcpy(v_out, vals.data(), n_out * 4);
    }
    std::memcpy(b_out, bounds.data(), bounds.size() * sizeof(int64_t));
    *out_slots = s_out;
    *out_vals = v_out;
    *out_bounds = b_out;
    *out_status = status;
    return 0;
}

void text_hash_free(int* slots, float* vals, int64_t* bounds,
                    unsigned char* status) {
    std::free(slots);
    std::free(vals);
    std::free(bounds);
    std::free(status);
}

}  // extern "C"
