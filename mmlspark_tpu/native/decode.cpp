// Host-side image decoding: JPEG (libjpeg) + PNG (libpng) -> BGR uint8.
//
// TPU-native replacement for the reference's OpenCV imgcodecs JNI decode
// (ImageReader.scala:25-40: Imgcodecs.imdecode per row inside a Spark UDF).
// Decode must stay host-side (bitstream parsing is irreducibly scalar); the
// decoded tensors then batch onto the device for every later op.  Output is
// BGR to preserve the reference's OpenCV byte order (ImageSchema.scala:18-23).
//
// Exposed as a plain C ABI consumed via ctypes (the NativeLoader-equivalent
// lives in mmlspark_tpu/native_loader.py, cf. NativeLoader.java:29-159).

#include <cstdint>
#include <atomic>
#include <csetjmp>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErrorMgr* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

void jpeg_silence(j_common_ptr, int) {}

bool is_jpeg(const unsigned char* buf, int64_t len) {
  return len >= 3 && buf[0] == 0xFF && buf[1] == 0xD8 && buf[2] == 0xFF;
}

bool is_png(const unsigned char* buf, int64_t len) {
  return len >= 8 && png_sig_cmp(buf, 0, 8) == 0;
}

}  // namespace

extern "C" {

// Probe dimensions. Returns 0 on success, fills (width, height, channels);
// channels is what decode_image will produce (3 = BGR, 1 = gray).
int image_dims(const unsigned char* buf, int64_t len, int* width, int* height,
               int* channels) {
  if (is_jpeg(buf, len)) {
    jpeg_decompress_struct cinfo;
    JpegErrorMgr jerr;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = jpeg_error_exit;
    jerr.pub.emit_message = jpeg_silence;
    if (setjmp(jerr.setjmp_buffer)) {
      jpeg_destroy_decompress(&cinfo);
      return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
                 static_cast<uint64_t>(len));
    jpeg_read_header(&cinfo, TRUE);
    *width = static_cast<int>(cinfo.image_width);
    *height = static_cast<int>(cinfo.image_height);
    *channels = cinfo.num_components == 1 ? 1 : 3;
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  if (is_png(buf, len)) {
    png_image image;
    memset(&image, 0, sizeof image);
    image.version = PNG_IMAGE_VERSION;
    if (!png_image_begin_read_from_memory(&image, buf,
                                          static_cast<size_t>(len))) {
      return -1;
    }
    *width = static_cast<int>(image.width);
    *height = static_cast<int>(image.height);
    *channels = (image.format & PNG_FORMAT_FLAG_COLOR) ? 3 : 1;
    png_image_free(&image);
    return 0;
  }
  return -2;  // unknown format
}

// Decode into caller-allocated out (height*width*channels bytes, BGR or
// gray row-major). Returns 0 on success.
int decode_image(const unsigned char* buf, int64_t len, unsigned char* out,
                 int width, int height, int channels) {
  if (is_jpeg(buf, len)) {
    jpeg_decompress_struct cinfo;
    JpegErrorMgr jerr;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = jpeg_error_exit;
    jerr.pub.emit_message = jpeg_silence;
    if (setjmp(jerr.setjmp_buffer)) {
      jpeg_destroy_decompress(&cinfo);
      return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
                 static_cast<uint64_t>(len));
    jpeg_read_header(&cinfo, TRUE);
    cinfo.out_color_space = channels == 1 ? JCS_GRAYSCALE : JCS_RGB;
    jpeg_start_decompress(&cinfo);
    if (static_cast<int>(cinfo.output_width) != width ||
        static_cast<int>(cinfo.output_height) != height) {
      jpeg_destroy_decompress(&cinfo);
      return -3;
    }
    const int row_bytes = width * channels;
    while (cinfo.output_scanline < cinfo.output_height) {
      unsigned char* row = out +
          static_cast<int64_t>(cinfo.output_scanline) * row_bytes;
      jpeg_read_scanlines(&cinfo, &row, 1);
    }
    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    if (channels == 3) {  // RGB -> BGR in place
      const int64_t n = static_cast<int64_t>(width) * height;
      for (int64_t i = 0; i < n; ++i) {
        unsigned char t = out[i * 3];
        out[i * 3] = out[i * 3 + 2];
        out[i * 3 + 2] = t;
      }
    }
    return 0;
  }
  if (is_png(buf, len)) {
    png_image image;
    memset(&image, 0, sizeof image);
    image.version = PNG_IMAGE_VERSION;
    if (!png_image_begin_read_from_memory(&image, buf,
                                          static_cast<size_t>(len))) {
      return -1;
    }
    image.format = channels == 1 ? PNG_FORMAT_GRAY : PNG_FORMAT_BGR;
    if (static_cast<int>(image.width) != width ||
        static_cast<int>(image.height) != height) {
      png_image_free(&image);
      return -3;
    }
    // Alpha channels composite against the existing buffer contents when no
    // background is given; zero it so transparent regions are black, not
    // whatever the caller's uninitialized allocation held.
    memset(out, 0, static_cast<size_t>(width) * height * channels);
    if (!png_image_finish_read(&image, nullptr, out, 0, nullptr)) {
      png_image_free(&image);
      return -1;
    }
    return 0;
  }
  return -2;
}

// Parallel batch decode: n independent buffers decoded by a thread pool
// (libjpeg/libpng handles are per-call, so decodes are embarrassingly
// parallel; the Python caller holds the GIL exactly once for the whole
// batch instead of once per image).  outs[i] must be pre-allocated to
// heights[i]*widths[i]*channels[i] bytes (probe with image_dims first).
// status[i] receives each image's decode_image return code; the function
// returns the number of failures.
int decode_batch(const unsigned char** bufs, const int64_t* lens,
                 unsigned char** outs, const int* widths, const int* heights,
                 const int* channels, int n, int n_threads, int* status) {
  if (n <= 0) return 0;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n) n_threads = n;
  std::atomic<int> next(0);
  std::atomic<int> failures(0);
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      status[i] = decode_image(bufs[i], lens[i], outs[i], widths[i],
                               heights[i], channels[i]);
      if (status[i] != 0) failures.fetch_add(1);
    }
  };
  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(n_threads) - 1);
  for (int t = 1; t < n_threads; ++t) threads.emplace_back(worker);
  worker();
  for (auto& th : threads) th.join();
  return failures.load();
}

}  // extern "C"
