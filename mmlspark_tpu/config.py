"""Global configuration tier: one registry for every MMLSPARK_TPU_* knob.

Counterpart of the reference's two config layers — the Typesafe-config
wrapper (Configuration.scala:18-51: packaged defaults overlaid by an
environment-pointed file) and the `defvar` env framework the build/install
system uses (tools/config.sh:53-60: every variable declared with defaults
and documented provenance).  Here a variable is declared exactly once with
its name, type, default, and doc; reads go through `get()` with precedence

    programmatic override (`set()`)  >  process environment  >  default

and `describe()` makes the whole surface discoverable (the reference prints
its defvar table the same way).  Modules never call os.environ for
MMLSPARK_TPU_* values directly — they import this registry.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable

_PREFIX = "MMLSPARK_TPU_"


@dataclasses.dataclass(frozen=True)
class ConfigVar:
    name: str              # full env name, MMLSPARK_TPU_*
    default: Any
    doc: str
    ptype: Callable = str  # parser applied to env-var strings

    def current(self) -> Any:
        if self.name in _overrides:
            return _overrides[self.name]
        raw = os.environ.get(self.name)
        if raw is None:
            return self.default
        return self.ptype(raw)


_registry: dict[str, ConfigVar] = {}
_overrides: dict[str, Any] = {}
_declared_by: dict[str, str] = {}  # var name -> declaring module


def register(name: str, default: Any = None, doc: str = "",
             ptype: Callable = str) -> ConfigVar:
    """Declare a config variable (idempotent for identical declarations)."""
    if not name.startswith(_PREFIX):
        raise ValueError(f"config vars are namespaced {_PREFIX}*; got {name!r}")
    existing = _registry.get(name)
    if existing is not None:
        if (existing.default, existing.doc, existing.ptype) != \
                (default, doc, ptype):
            raise ValueError(f"{name} already registered with different "
                             f"default/doc/ptype; one declaration per "
                             f"variable")
        return existing  # identical re-declaration: keep the one instance
    var = ConfigVar(name, default, doc, ptype)
    _registry[name] = var
    # provenance, so generated docs can list the FRAMEWORK's variables
    # without picking up test/application declarations made in-process
    import sys
    _declared_by[name] = sys._getframe(1).f_globals.get("__name__", "")
    return var


def get(name: str) -> Any:
    """Typed current value: override > environment > default."""
    if name not in _registry:
        raise KeyError(f"unregistered config var {name!r}; known: "
                       f"{sorted(_registry)}")
    return _registry[name].current()


def set(name: str, value: Any) -> None:  # noqa: A001 - mirrors Configuration.set
    """Programmatic override (highest precedence); None removes it."""
    if name not in _registry:
        raise KeyError(f"unregistered config var {name!r}")
    if value is None:
        _overrides.pop(name, None)
    else:
        _overrides[name] = value


def describe() -> list[dict]:
    """Every registered variable with default, doc, current value, and the
    module that declared it (so generated docs can keep test/application
    declarations made in-process out of the framework's reference table)."""
    return [{"name": v.name, "default": v.default, "doc": v.doc,
             "current": v.current(),
             "declared_by": _declared_by.get(v.name, "")} for v in
            sorted(_registry.values(), key=lambda v: v.name)]


def _intp(s: str) -> int:
    return int(s)


def _floatp(s: str) -> float:
    return float(s)


# --------------------------------------------------------------------------
# the framework's variables (one declaration each; consumers import these)
# --------------------------------------------------------------------------

LOG_LEVEL = register(
    "MMLSPARK_TPU_LOG_LEVEL", default=None,
    doc="When set (DEBUG/INFO/...), the framework manages its own log "
        "output: root logger level + stderr handler (observe/logging.py). "
        "Unset: standard library behavior, the application configures.")

NATIVE_CACHE = register(
    "MMLSPARK_TPU_NATIVE_CACHE", default=None,
    doc="Directory for compiled native (C++) decoder artifacts; default "
        "~/.cache/mmlspark_tpu (native_loader.py).")

COORDINATOR = register(
    "MMLSPARK_TPU_COORDINATOR", default=None,
    doc="host:port of the jax.distributed coordinator for multi-host runs "
        "(the reference's MPI hostfile analogue, parallel/distributed.py).")

NUM_PROCESSES = register(
    "MMLSPARK_TPU_NUM_PROCESSES", default=None, ptype=_intp,
    doc="Total process count of the multi-host run.")

PROCESS_ID = register(
    "MMLSPARK_TPU_PROCESS_ID", default=None, ptype=_intp,
    doc="This process's index in the multi-host run (0 = coordinator).")

COLLECTIVE_TIMEOUT_S = register(
    "MMLSPARK_TPU_COLLECTIVE_TIMEOUT_S", default=600.0, ptype=_floatp,
    doc="Bounded wait for named multi-host collectives (barriers, "
        "checkpoint broadcast/gather): on expiry a CollectiveTimeoutError "
        "names the operation instead of the job hanging forever "
        "(parallel/distributed.py).")

TEST_PLATFORM = register(
    "MMLSPARK_TPU_TEST_PLATFORM", default="cpu",
    doc="Test harness: 'cpu' forces the 8-virtual-device CPU mesh; 'tpu' "
        "runs the suite (incl. perf floors) on real chips (tests/conftest.py).")

TEST_BUDGET_S = register(
    "MMLSPARK_TPU_TEST_BUDGET_S", default=30.0, ptype=_floatp,
    doc="Per-test duration alert budget in seconds (reference "
        "TestBase.scala:65 alerts at 3s; XLA compiles are ~10x that).")

TELEMETRY = register(
    "MMLSPARK_TPU_TELEMETRY", default=None,
    doc="Telemetry kill switch: '0'/'off'/'false' makes run_telemetry() "
        "blocks inert (no spans, no files, hot loops keep the zero-cost "
        "fast path); unset or anything else leaves them live "
        "(observe/telemetry.py).")

TELEMETRY_DIR = register(
    "MMLSPARK_TPU_TELEMETRY_DIR", default=None,
    doc="Default output directory for run_telemetry(): run.jsonl event "
        "stream + run_summary.json land here when the block passes no "
        "dir. Unset + no explicit dir: in-memory ring only, no files.")

COMPILATION_CACHE = register(
    "MMLSPARK_TPU_COMPILATION_CACHE", default=None,
    doc="Directory for JAX's persistent XLA compilation cache; when set, "
        "warm restarts (resume-after-preemption, repeated bench runs) load "
        "compiled executables from disk instead of re-lowering "
        "(docs/performance.md). Unset: in-memory jit cache only.")


def setup_compilation_cache() -> Any:
    """Point JAX's persistent compilation cache at the configured directory.

    Called at package import (mmlspark_tpu/__init__.py) and safe to call
    again after `set('MMLSPARK_TPU_COMPILATION_CACHE', ...)`.  Returns the
    effective directory, or None when the knob is unset or this JAX build
    has no persistent-cache support (older builds: silently skipped — the
    cache is an optimization, never a requirement).
    """
    path = COMPILATION_CACHE.current()
    if not path:
        return None
    path = os.path.abspath(os.path.expanduser(str(path)))
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # cache every executable: the default thresholds skip sub-second
        # compiles, but warm-restart wins here come precisely from the many
        # small per-shape programs the scoring/training loops accumulate
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        return None
    return path
