from mmlspark_tpu.train.config import TrainerConfig
from mmlspark_tpu.train.trainer import Trainer, TrainState
from mmlspark_tpu.train.learner import TPULearner
