from mmlspark_tpu.train.config import TrainerConfig
from mmlspark_tpu.train.trainer import Trainer, TrainState
from mmlspark_tpu.train.sweep import (PopulationState, PopulationTrainer,
                                      SweepResult)
from mmlspark_tpu.train.learner import TPULearner
from mmlspark_tpu.train.supervisor import (RecoveryBudgetExceeded,
                                           RecoveryPolicy,
                                           RecoverySupervisor)
