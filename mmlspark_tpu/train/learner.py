"""TPULearner: the Estimator face of distributed training.

Replacement for the reference's CNTKLearner (CNTKLearner.scala:52-162): the
same pipeline contract — `fit(table with features+label) -> scoring model` —
but instead of exporting data to CNTKText files and shelling out to
`cntk`/`mpiexec`, it trains in-process on the mesh and wraps the result as a
TPUModel, exactly as CNTKLearner wraps its output `.model` file as a
CNTKModel (CNTKLearner.scala:158-161).  Fine-tuning a zoo model = setting
`initial_bundle` (the localHdfsMount/model-download dance collapses away).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Estimator
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle, load_bundle, save_bundle
from mmlspark_tpu.models.tpu_model import TPUModel
from mmlspark_tpu.train.config import TrainerConfig
from mmlspark_tpu.train.trainer import Trainer


class TPULearner(Estimator):
    featuresCol = Param("features", "features column", ptype=str)
    labelCol = Param("label", "label column", ptype=str)
    outputCol = Param("output", "output column of the fitted model", ptype=str)
    config = Param(None, "TrainerConfig as a JSON dict", ptype=dict)
    logEvery = Param(50, "epoch logging interval", ptype=int)

    def __init__(self, config: Optional[TrainerConfig] = None, **kwargs):
        super().__init__(**kwargs)
        if config is not None:
            self.set("config", config.to_json())
        self._initial_bundle: Optional[ModelBundle] = None
        self._mesh = None

    def trainer_config(self) -> TrainerConfig:
        return TrainerConfig.from_json(self.config) if self.config \
            else TrainerConfig()

    def set_initial_bundle(self, bundle: ModelBundle) -> "TPULearner":
        """Warm-start weights (transfer learning / fine-tune flow)."""
        self._initial_bundle = bundle
        return self

    def set_mesh(self, mesh) -> "TPULearner":
        self._mesh = mesh
        return self

    def fit(self, table: DataTable) -> TPUModel:
        cfg = self.trainer_config()
        # drop rows with missing labels (reference CNTKLearner.scala:58)
        clean = table.drop_nulls([self.labelCol])
        x = np.asarray(clean[self.featuresCol], np.float32)
        y = np.asarray(clean[self.labelCol])
        if y.dtype == object:
            raise TypeError(
                f"label column '{self.labelCol}' must be numeric; "
                "encode categoricals first (see core.schema.make_categorical)")
        trainer = Trainer(cfg, mesh=self._mesh)
        bundle = trainer.fit_arrays(
            x, y, initial_bundle=self._initial_bundle,
            log_every=self.logEvery, log_fn=_log)
        model = TPUModel(bundle, inputCol=self.featuresCol,
                         outputCol=self.outputCol,
                         miniBatchSize=max(cfg.batch_size, 1))
        model._history = list(trainer.history)
        return model

    def _save_extra(self, path: str) -> None:
        if self._initial_bundle is not None:
            save_bundle(self._initial_bundle, f"{path}/initial_bundle")

    def _load_extra(self, path: str) -> None:
        import os
        self._initial_bundle = (load_bundle(f"{path}/initial_bundle")
                                if os.path.exists(f"{path}/initial_bundle")
                                else None)
        self._mesh = None


def _log(msg: str) -> None:
    from mmlspark_tpu.observe import get_logger
    get_logger("train").info(msg)
