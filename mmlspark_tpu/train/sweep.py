"""Vectorized population training: N hyperparameter-sweep members in
ONE compiled program.

The auto-ML surface (`FindBestModel`/`TrainClassifier`) used to train
candidates sequentially — each `fit` a tiny program leaving the MXU
idle between dispatches, the TPU-era version of the reference spinning
up one `mpiexec` per candidate (CNTKLearner.scala:52-162).  SparkNet's
answer was to fan candidates across a cluster; the TPU-native answer is
to stack every member's param/opt-state trees on a leading population
axis, broadcast the shared data batch, and `vmap` the train step so all
members advance inside one XLA program per step.

Mechanics:

  * member k's init RNG is `fold_in(key(seed_k), k)` — independent of
    the population size, so a member's loss curve does not move when
    other members are added or culled;
  * per-member learning rates ride through `vmap` as traced scalars
    into the SAME optax chain a plain `Trainer` builds
    (train/trainer.py `build_optimizer`), keeping a member's update
    arithmetic equivalent to an ordinary fit at that rate;
  * successive halving culls trailing members at rung boundaries by a
    per-member `active` mask: the update still runs but `jnp.where`
    freezes a culled member's params/opt-state/batch-stats.  Shapes and
    dtypes never change, so culling never recompiles and never
    re-stacks;
  * `vmap` sits OUTSIDE the `use_mesh`-scoped member step, composing
    with the PR-12 partition registry: an underfilling member can still
    shard over the 'model' axis, and the batch keeps its 'data'-axis
    sharding with the population axis unconstrained.

Single-controller by design: the sweep trains many small models on one
process's mesh; multi-host jobs should shard the CANDIDATE GRID across
hosts (one PopulationTrainer each), not one population across hosts.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from mmlspark_tpu import config
from mmlspark_tpu.models.bundle import ModelBundle, _to_plain
from mmlspark_tpu.models.definitions import build_model
from mmlspark_tpu.observe import get_logger
from mmlspark_tpu.observe.spans import active_timings, monotonic, span_on
from mmlspark_tpu.observe.telemetry import active_run
from mmlspark_tpu.observe.trace import active_tracer, current_span_id
from mmlspark_tpu.parallel.bridge import (put_like, put_sharded, put_tree,
                                          put_tree_like, snapshot_tree,
                                          stack_trees, unstack_member)
from mmlspark_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, batch_sharding,
                                        make_mesh, replicated)
from mmlspark_tpu.parallel.partition import (named_sharding, rules_to_json,
                                             use_mesh)
from mmlspark_tpu.parallel.partition import DEFAULT_RULES
from mmlspark_tpu.resilience.checkpoints import (checkpoint_name,
                                                 latest_valid_checkpoint)
from mmlspark_tpu.resilience.ckpt_writer import (CheckpointWriter,
                                                 read_checkpoint)
from mmlspark_tpu.train.config import TrainerConfig
from mmlspark_tpu.train.trainer import (Trainer, _epoch_order, _make_loss,
                                        _param_sharding_rule, build_optimizer)
from jax.sharding import PartitionSpec as P

SWEEP_HALVING_RUNGS = config.register(
    "MMLSPARK_TPU_SWEEP_HALVING_RUNGS", 0, ptype=int,
    doc="population training: successive-halving rung count (0 = no "
        "culling; rungs split the step budget evenly, each culls the "
        "trailing members by recent loss — train/sweep.py)")
SWEEP_CULL_FRACTION = config.register(
    "MMLSPARK_TPU_SWEEP_CULL_FRACTION", 0.5, ptype=float,
    doc="population training: fraction of still-active members culled "
        "at each halving rung (mask-frozen, never re-stacked)")
SWEEP_MIN_ACTIVE = config.register(
    "MMLSPARK_TPU_SWEEP_MIN_ACTIVE", 1, ptype=int,
    doc="population training: floor of active members a halving rung "
        "may not cull below")


@struct.dataclass
class PopulationState:
    """A `TrainState` with a leading population axis on every tree leaf,
    plus the per-member vmapped scalars (learning rate, active mask)."""
    step: jax.Array        # scalar int32, shared — members step in lockstep
    params: Any            # stacked: leaf shape (N, ...)
    opt_state: Any         # stacked optax state
    batch_stats: Any       # stacked ({} for stateless models)
    lr: jax.Array          # (N,) float32 per-member learning rate
    active: jax.Array      # (N,) float32 mask; 0 = culled (frozen)


@dataclasses.dataclass
class SweepResult:
    """What a population fit hands back: the final stacked state, every
    member's loss curve, and the winner unstacked into a normal bundle."""
    state: PopulationState
    member_loss: np.ndarray          # (steps, N) per-step per-member loss
    lrs: np.ndarray                  # (N,) the rates trained at
    active: np.ndarray               # (N,) final mask (1 = survived)
    best_member: int
    _trainer: "PopulationTrainer"

    @property
    def population(self) -> int:
        return int(self.lrs.shape[0])

    def final_losses(self) -> np.ndarray:
        """Each member's final-step training loss (culled members hold the
        loss their frozen params still produce)."""
        return self.member_loss[-1]

    def member_bundle(self, k: int) -> ModelBundle:
        return self._trainer.member_bundle(self.state, k)

    def winner_bundle(self) -> ModelBundle:
        return self.member_bundle(self.best_member)


class PopulationTrainer:
    """Trains a population of sweep members with one vmapped step.

    `members` is either an int (population size N; every member gets the
    config's learning rate — useful for seed sweeps) or a sequence of
    per-member dicts, each accepting:

        learning_rate  (default: config.learning_rate)
        seed           (default: config.seed; the member's init key is
                        fold_in(key(seed), member_id) either way)

    The shared data batch, epoch ordering, and batch clamping follow
    `Trainer.fit_arrays` exactly, so a member's step sequence matches
    the plain trainer's at the same config.
    """

    def __init__(self, trainer_config: TrainerConfig,
                 members: Union[int, Sequence[dict]], mesh=None,
                 halving_rungs: Optional[int] = None,
                 cull_fraction: Optional[float] = None,
                 min_active: Optional[int] = None):
        self.config = trainer_config
        if jax.process_count() > 1:
            raise NotImplementedError(
                "population training is single-controller; shard the "
                "candidate grid across hosts, not one population")
        if trainer_config.pipeline_stages > 1:
            raise ValueError(
                "population training does not compose with pipeline "
                "parallelism (the stage ring owns the 'model' axis)")
        if isinstance(members, int):
            if members < 1:
                raise ValueError("population size must be >= 1")
            members = [{} for _ in range(members)]
        self.members = [dict(m) for m in members]
        if not self.members:
            raise ValueError("population needs at least one member")
        self.module = build_model(trainer_config.architecture,
                                  trainer_config.model_config)
        self.mesh = mesh if mesh is not None else make_mesh(
            trainer_config.mesh)
        self._loss = _make_loss(trainer_config.loss)
        import inspect
        sig = inspect.signature(type(self.module).__call__)
        self._has_train_arg = "train" in sig.parameters
        self.halving_rungs = int(SWEEP_HALVING_RUNGS.current()
                                 if halving_rungs is None else halving_rungs)
        self.cull_fraction = float(SWEEP_CULL_FRACTION.current()
                                   if cull_fraction is None
                                   else cull_fraction)
        self.min_active = int(SWEEP_MIN_ACTIVE.current()
                              if min_active is None else min_active)
        if not 0.0 < self.cull_fraction < 1.0:
            raise ValueError("cull_fraction must be in (0, 1)")
        self.history: list[dict] = []
        self._writers: dict[str, CheckpointWriter] = {}

    # -- init -----------------------------------------------------------
    @property
    def population(self) -> int:
        return len(self.members)

    def member_lr(self, k: int) -> float:
        return float(self.members[k].get("learning_rate",
                                         self.config.learning_rate))

    def _member_key(self, k: int) -> jax.Array:
        seed = int(self.members[k].get("seed", self.config.seed))
        return jax.random.fold_in(jax.random.key(seed), k)

    def member_init_variables(self, k: int, input_shape: tuple,
                              input_dtype=np.float32) -> dict:
        """Member k's fresh-init variables (host arrays) — the same tree
        the population stacks at slot k, unstacked.  Parity harnesses
        warm-start a plain Trainer from this to compare update
        arithmetic without re-deriving the fold_in init."""
        x = np.zeros(input_shape, input_dtype)
        variables = _to_plain(self.module.init(self._member_key(k), x))
        return jax.tree_util.tree_map(np.asarray, variables)

    def member_init_bundle(self, k: int, input_shape: tuple,
                           input_dtype=np.float32) -> ModelBundle:
        """Member k's init as a warm-start bundle for a plain Trainer."""
        return ModelBundle.from_module(
            self.module,
            self.member_init_variables(k, input_shape, input_dtype))

    def _stacked_shardings(self, stacked_params):
        """Per-leaf shardings for the population tree: the registry rule
        on the UNSTACKED member shape, with the population axis prepended
        unconstrained — a member sharded over 'model' stays sharded."""
        rule = _param_sharding_rule(self.mesh, self.config.tensor_parallel,
                                    self.config.expert_parallel,
                                    getattr(self.config, "partition_rules",
                                            None))

        def stacked(path, leaf):
            member = jax.ShapeDtypeStruct(np.shape(leaf)[1:],
                                          np.asarray(leaf).dtype)
            spec = rule(path, member).spec
            return named_sharding(self.mesh, P(None, *spec))

        return jax.tree_util.tree_map_with_path(stacked, stacked_params)

    def init_state(self, input_shape: tuple, total_steps: int = 1,
                   input_dtype=np.float32) -> PopulationState:
        """Stack every member's fresh init (and eager optax init) into
        one sharded PopulationState."""
        n = self.population
        tx = build_optimizer(self.config, total_steps)
        params_list, stats_list, opt_list = [], [], []
        for k in range(n):
            variables = self.member_init_variables(k, input_shape,
                                                   input_dtype)
            params_list.append(variables["params"])
            stats_list.append(variables.get("batch_stats", {}))
            # optax init is lr-independent, so the host-side member init
            # stacks exactly like params (counts collapse to equal scalars)
            opt_list.append(jax.tree_util.tree_map(
                np.asarray, jax.device_get(tx.init(variables["params"]))))
        params = stack_trees(params_list)
        opt_state = stack_trees(opt_list)
        batch_stats = stack_trees(stats_list) if stats_list[0] else {}
        # eager sharded placement, mirroring Trainer.init_state: params by
        # the (population-prefixed) registry rule, everything else replicated
        params = put_tree(params, self._stacked_shardings(params))
        rep = replicated(self.mesh)
        opt_state = put_tree(opt_state, jax.tree_util.tree_map(
            lambda _: rep, opt_state))
        batch_stats = put_tree(batch_stats, jax.tree_util.tree_map(
            lambda _: rep, batch_stats))
        lr = np.asarray([self.member_lr(k) for k in range(n)], np.float32)
        active = np.ones(n, np.float32)
        return PopulationState(
            step=jnp.asarray(0, jnp.int32),
            params=params, opt_state=opt_state, batch_stats=batch_stats,
            lr=put_sharded(lr, rep), active=put_sharded(active, rep))

    # -- the compiled step ----------------------------------------------
    def make_population_step(self, total_steps: int):
        """jit(vmap(member step)): one program advancing all N members.

        The member step runs under `use_mesh`, so the module forward's
        sharding constraints bake this mesh in; `vmap` wraps it from the
        OUTSIDE with the data batch broadcast (in_axes=None) and the
        member trees/scalars batched (in_axes=0)."""
        module, loss_fn = self.module, self._loss
        has_train = self._has_train_arg
        cfg, mesh = self.config, self.mesh
        aux_w = float(cfg.aux_loss_weight)

        def member_step(params, opt_state, batch_stats, lr, active,
                        x, y, mask):
            # the same chain a plain Trainer builds, with this member's
            # rate riding in as a traced scalar
            tx = build_optimizer(cfg, total_steps, learning_rate=lr)

            def compute(p):
                variables = {"params": p}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                if has_train:
                    out, mut = module.apply(
                        variables, x, train=True,
                        mutable=["batch_stats", "losses", "metrics"])
                    new_stats = mut.get("batch_stats", batch_stats)
                else:
                    out, mut = module.apply(variables, x,
                                            mutable=["losses", "metrics"])
                    new_stats = batch_stats
                loss = loss_fn(out, y, mask)
                if aux_w:
                    loss = loss + aux_w * sum(
                        jnp.asarray(v).sum() for v in
                        jax.tree_util.tree_leaves(mut.get("losses", {})))
                return loss, new_stats

            (loss, new_stats), grads = \
                jax.value_and_grad(compute, has_aux=True)(params)
            updates, new_opt = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            # the halving freeze: a culled member still traces the same
            # program (no recompile) but keeps its old state byte-for-byte
            keep = active > 0

            def freeze(new, old):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(keep, a, b), new, old)

            return (freeze(new_params, params), freeze(new_opt, opt_state),
                    freeze(new_stats, batch_stats), loss)

        def meshed_member_step(*args):
            with use_mesh(mesh):
                return member_step(*args)

        vmapped = jax.vmap(meshed_member_step,
                           in_axes=(0, 0, 0, 0, 0, None, None, None))

        def population_step(state: PopulationState, x, y, mask):
            new_params, new_opt, new_stats, losses = vmapped(
                state.params, state.opt_state, state.batch_stats,
                state.lr, state.active, x, y, mask)
            return PopulationState(
                step=state.step + 1, params=new_params, opt_state=new_opt,
                batch_stats=new_stats, lr=state.lr,
                active=state.active), losses

        return jax.jit(population_step, donate_argnums=(0,))

    # -- scoring --------------------------------------------------------
    def score_population(self, state: PopulationState,
                         x: np.ndarray) -> np.ndarray:
        """Stacked inference logits, shape (N, rows, ...): ONE vmapped
        forward scores every member — the batched candidate evaluation
        FindBestModel feeds to `classification_report_batch` instead of
        N transform round-trips."""
        module, mesh = self.module, self.mesh
        has_train = self._has_train_arg

        def member_apply(params, batch_stats, xb):
            with use_mesh(mesh):
                variables = {"params": params}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                if has_train:
                    return module.apply(variables, xb, train=False)
                return module.apply(variables, xb)

        fn = jax.jit(jax.vmap(member_apply, in_axes=(0, 0, None)))
        xb = put_sharded(np.asarray(x), batch_sharding(self.mesh))
        return np.asarray(jax.device_get(
            fn(state.params, state.batch_stats, xb)))

    # -- the loop --------------------------------------------------------
    def fit_arrays(self, x: np.ndarray, y: np.ndarray,
                   ckpt_dir: Optional[str] = None,
                   resume: bool = False) -> SweepResult:
        """Train the whole population on shared data; returns the final
        stacked state plus per-member loss curves and the winner.

        Data order, batch clamping, and the rng stream are identical to
        `Trainer.fit_arrays` at the same config, so curves line up with
        plain fits.  `ckpt_dir` + config.checkpoint_every_steps write
        rotation checkpoints of the WHOLE population (one file, stacked
        trees + lr + active); `resume=True` restarts a mid-sweep
        population from the newest valid one, replaying the same data
        order and skipping completed steps.
        """
        cfg = self.config
        n_pop = self.population
        ckpt_dir = ckpt_dir if ckpt_dir is not None else cfg.checkpoint_dir
        n = len(x)
        data_size = self.mesh.shape[DATA_AXIS]
        bs = cfg.batch_size
        bs = max(bs - bs % data_size, data_size)
        steps_per_epoch = max(1, (n + bs - 1) // bs)
        total_steps = steps_per_epoch * cfg.epochs
        self._effective_batch_size = bs

        state = self.init_state((1,) + x.shape[1:], total_steps,
                                input_dtype=np.asarray(x).dtype)
        skip_until = 0
        if resume and ckpt_dir and \
                latest_valid_checkpoint(ckpt_dir) is not None:
            state = self.restore_checkpoint(state, ckpt_dir)
            skip_until = int(state.step)
            get_logger("train").info(
                "sweep resuming from checkpoint at step %d", skip_until)
        step_fn = self.make_population_step(total_steps)
        x_sh = batch_sharding(self.mesh)
        rng = np.random.default_rng(cfg.seed)

        # rung boundaries: the step budget split evenly across rungs, the
        # last boundary strictly before the end so the final span trains
        # the survivors
        rungs = []
        if self.halving_rungs > 0:
            span = total_steps / (self.halving_rungs + 1)
            rungs = sorted({int(span * (i + 1))
                            for i in range(self.halving_rungs)})
            rungs = [r for r in rungs if 0 < r < total_steps]

        tracer = active_tracer()
        run = active_run()
        timings = active_timings()
        fit_span = tracer.span(
            "sweep.fit", parent=current_span_id(), cat="phase",
            architecture=cfg.architecture, population=n_pop,
            total_steps=total_steps, batch_size=bs,
            halving_rungs=len(rungs)) if tracer is not None else None
        fit_id = fit_span.span_id if fit_span is not None else None
        if run is not None:
            run.record_sweep({
                "event": "start", "population": n_pop,
                "total_steps": total_steps,
                "lrs": [self.member_lr(k) for k in range(n_pop)],
                "rungs": rungs, "resumed_at": skip_until})

        t0 = monotonic()
        active_host = np.asarray(jax.device_get(state.active), np.float32)
        loss_rows: list = []        # one (N,) device array per executed step
        rung_start = 0              # index into loss_rows of the rung window
        epoch_losses: list = []
        cur_epoch = -1
        first_exec = True

        def finish_epoch(epoch: int) -> None:
            if epoch < 0 or not epoch_losses:
                return
            fetched = np.asarray(jax.device_get(epoch_losses), np.float32)
            mean = fetched.mean(axis=0)  # (N,)
            act = active_host > 0
            rec = {"epoch": epoch,
                   "loss": float(mean[act].mean()) if act.any()
                   else float(mean.mean()),
                   "member_loss": [float(v) for v in mean],
                   "wall_s": monotonic() - t0}
            self.history.append(rec)

        def cull(step_c: int) -> None:
            """One halving rung: rank active members by their mean loss
            since the previous rung; freeze the trailing cull_fraction."""
            nonlocal active_host, rung_start, state
            window = loss_rows[rung_start:]
            rung_start = len(loss_rows)
            if not window:
                return
            mean = np.asarray(jax.device_get(window),
                              np.float32).mean(axis=0)
            alive = np.flatnonzero(active_host > 0)
            n_keep = max(self.min_active,
                         len(alive) - max(1, int(len(alive)
                                                 * self.cull_fraction)))
            if n_keep >= len(alive):
                return
            order = alive[np.argsort(mean[alive], kind="stable")]
            culled = order[n_keep:]
            active_host = active_host.copy()
            active_host[culled] = 0.0
            # same shape/dtype/sharding → the compiled step is reused
            state = state.replace(
                active=put_like(active_host, state.active))
            if run is not None:
                run.record_sweep({
                    "event": "cull", "step": step_c,
                    "culled": [int(c) for c in culled],
                    "survivors": [int(s) for s in order[:n_keep]],
                    "window_loss": [float(v) for v in mean]})
            get_logger("train").info(
                "sweep rung at step %d: culled members %s (%d survive)",
                step_c, [int(c) for c in culled], n_keep)

        step_c = 0
        for epoch in range(cfg.epochs):
            order = _epoch_order(rng, epoch, n, n, cfg.shuffle_each_epoch)
            if epoch != cur_epoch:
                finish_epoch(cur_epoch)
                cur_epoch = epoch
                epoch_losses = []
            for start in range(0, n, bs):
                if step_c < skip_until:
                    # completed before the checkpoint being resumed; the
                    # rng stream above still advanced identically, and a
                    # rung crossed before the save already took effect in
                    # the restored active mask
                    step_c += 1
                    if step_c in rungs:
                        rung_start = len(loss_rows)
                    continue
                with span_on(timings, "host"):
                    idx = order[start:start + bs]
                    valid = len(idx)
                    if valid < bs:
                        idx = np.concatenate(
                            [idx, np.resize(order, bs - valid)])
                    mask = np.zeros(bs, np.float32)
                    mask[:valid] = 1.0
                    xh, yh = x[idx], y[idx]
                with span_on(timings, "transfer"):
                    xb = put_sharded(xh, x_sh)
                    yb = put_sharded(yh, x_sh)
                    mask_d = put_sharded(mask, x_sh)
                if tracer is None:
                    with span_on(timings, "compute"):
                        state, losses = step_fn(state, xb, yb, mask_d)
                else:
                    with tracer.span(
                            "train.step", parent=fit_id, cat="step",
                            step=step_c, epoch=epoch, population=n_pop,
                            first_step_compile=first_exec) as sp, \
                            span_on(timings, "compute"):
                        state, losses = step_fn(state, xb, yb, mask_d)
                        fetched = np.asarray(jax.device_get(losses),
                                             np.float32)
                        act = active_host > 0
                        sp.attrs["loss"] = float(
                            fetched[act].mean() if act.any()
                            else fetched.mean())
                        sp.attrs["member_loss"] = [
                            round(float(v), 6) for v in fetched]
                        sp.attrs["active_members"] = int(act.sum())
                first_exec = False
                loss_rows.append(losses)
                epoch_losses.append(losses)
                step_c += 1
                if step_c in rungs:
                    cull(step_c)
                if ckpt_dir and cfg.checkpoint_every_steps and \
                        step_c % cfg.checkpoint_every_steps == 0:
                    self.save_checkpoint(
                        state, ckpt_dir, step=step_c,
                        sync=not cfg.async_checkpointing)
        finish_epoch(cur_epoch)
        self._close_writers()
        if ckpt_dir:
            self.save_checkpoint(state, ckpt_dir, sync=True)
            self._close_writers()

        member_loss = np.asarray(jax.device_get(loss_rows), np.float32) \
            if loss_rows else np.zeros((0, n_pop), np.float32)
        # winner: best mean loss over the final epoch among survivors
        tail = member_loss[-max(1, steps_per_epoch):] if len(member_loss) \
            else np.zeros((1, n_pop), np.float32)
        tail_mean = tail.mean(axis=0)
        ranked = np.where(active_host > 0, tail_mean, np.inf)
        best = int(np.argmin(ranked))
        if run is not None:
            for k in range(n_pop):
                run.record_sweep({
                    "event": "member_final", "member": k,
                    "lr": self.member_lr(k),
                    "active": bool(active_host[k] > 0),
                    "final_loss": float(member_loss[-1, k])
                    if len(member_loss) else None})
            run.record_sweep({"event": "winner", "member": best,
                              "final_loss": float(tail_mean[best])})
        if fit_span is not None:
            fit_span.attrs["winner"] = best
            fit_span.finish()
        self._last_state = state
        return SweepResult(state=state, member_loss=member_loss,
                           lrs=np.asarray([self.member_lr(k)
                                           for k in range(n_pop)],
                                          np.float32),
                           active=active_host.copy(), best_member=best,
                           _trainer=self)

    # -- unstacking ------------------------------------------------------
    def member_bundle(self, state: PopulationState, k: int) -> ModelBundle:
        """Slice member k out of the stacked state into an ordinary
        ModelBundle — loadable by `TPUModel`, fine-tunable by `Trainer`,
        indistinguishable from a sequentially-trained model."""
        variables = {"params": unstack_member(state.params, k)}
        if state.batch_stats:
            variables["batch_stats"] = unstack_member(state.batch_stats, k)
        rules = getattr(self.config, "partition_rules", None) \
            or DEFAULT_RULES
        metadata = {
            "steps": int(state.step),
            "sweep": {"member": int(k), "population": self.population,
                      "learning_rate": self.member_lr(k)},
            "partition": {
                "rules": rules_to_json(rules),
                "mesh": {"data": int(self.mesh.shape.get(DATA_AXIS, 1)),
                         "model": int(self.mesh.shape.get(MODEL_AXIS, 1))},
            },
        }
        return ModelBundle.from_module(self.module, variables,
                                       metadata=metadata)

    def member_trainer(self, k: int) -> Trainer:
        """A plain Trainer configured exactly as member k (its learning
        rate and seed) — the sequential half of parity checks."""
        cfg = dataclasses.replace(
            self.config,
            learning_rate=self.member_lr(k),
            seed=int(self.members[k].get("seed", self.config.seed)))
        return Trainer(cfg, mesh=self.mesh)

    # -- checkpoint / resume ---------------------------------------------
    def _writer_for(self, ckpt_dir: str) -> CheckpointWriter:
        writer = self._writers.get(ckpt_dir)
        if writer is None:
            writer = self._writers[ckpt_dir] = CheckpointWriter(ckpt_dir)
        return writer

    def _close_writers(self) -> None:
        for writer in self._writers.values():
            writer.close(best_effort=True)
        self._writers.clear()

    def _state_tree(self, state: PopulationState) -> dict:
        return {"step": state.step, "params": state.params,
                "opt_state": state.opt_state,
                "batch_stats": state.batch_stats,
                "lr": state.lr, "active": state.active}

    def save_checkpoint(self, state: PopulationState, ckpt_dir: str, *,
                        step: Optional[int] = None,
                        sync: bool = True) -> str:
        """One rotation checkpoint of the WHOLE population (stacked trees
        + lr + active mask in a single file), riding the background
        writer exactly like Trainer.save_checkpoint."""
        dev = snapshot_tree(self._state_tree(state))
        step = int(state.step) if step is None else int(step)
        meta = {"step": step, "population": self.population,
                "effective_batch_size": getattr(
                    self, "_effective_batch_size", None),
                "seed": int(self.config.seed), "sweep": True, "format": 1}
        path = self._writer_for(ckpt_dir).submit(step, dev, meta=meta,
                                                 sync=sync)
        return path if path else os.path.join(ckpt_dir,
                                              checkpoint_name(step))

    def restore_checkpoint(self, state: PopulationState,
                           ckpt_dir: str) -> PopulationState:
        """Restore a mid-sweep population from the newest valid
        checkpoint: stacked arrays re-committed onto the live state's
        shardings (put_tree_like), the active mask included — culls that
        happened before the save stay culled after the resume."""
        path = latest_valid_checkpoint(ckpt_dir)
        if path is None:
            raise FileNotFoundError(f"no valid checkpoint in {ckpt_dir}")
        template = jax.tree_util.tree_map(
            lambda a: np.zeros(np.shape(a), a.dtype),
            self._state_tree(state))
        restored = read_checkpoint(template, path)
        return PopulationState(
            step=put_like(jnp.asarray(restored["step"], jnp.int32),
                          state.step, mesh=self.mesh),
            params=put_tree_like(restored["params"], state.params,
                                 mesh=self.mesh),
            opt_state=put_tree_like(restored["opt_state"], state.opt_state,
                                    mesh=self.mesh),
            batch_stats=put_tree_like(restored["batch_stats"],
                                      state.batch_stats, mesh=self.mesh),
            lr=put_like(restored["lr"], state.lr, mesh=self.mesh),
            active=put_like(restored["active"], state.active,
                            mesh=self.mesh))
