"""Recovery supervisor: close the detect→react loop around Trainer.fit_arrays.

PR 6's numerics probes can *detect* a poisoned step — `halt_on_nonfinite`
raises `NonFiniteError` before the checkpoint rotation promotes the bad
state — but the only response was to die and page a human.  The
supervisor is the react half: on a recoverable failure it

  1. **rolls back** — the next attempt resumes from the newest VALID
     checkpoint (which, by the raise-before-write contract, is the last
     finite/pre-divergence state);
  2. **applies a RecoveryPolicy** — skip the offending data window
     (the steps between the restore point and the failure advance the
     step counter but feed no data: the loss-scaling "skip step"
     convention), optionally re-fold the data-order RNG so retried
     shuffles draw different batches, optionally back the learning rate
     off per recovery;
  3. **resumes** — a fresh Trainer picks up from the restored step and
     runs to the ORIGINAL configured step count;
  4. **gives up cleanly** — past `max_recoveries` it raises
     `RecoveryBudgetExceeded` with the full machine-readable timeline,
     and the newest checkpoint on disk is still the last healthy state.

Failures handled: `NonFiniteError` (numerics probe), `DivergenceError`
(loss-spike detector with halt_on_divergence), `HungStepError` (the step
watchdog, TrainerConfig.step_timeout_s).  `Preempted` is NOT a failure:
by default it re-raises (the job runner owns process restarts); with
`resume_on_preemption=True` the supervisor resumes in-process — the mode
the chaos scenario suite uses to drill preemption without a runner.

Every decision lands three ways: a `recovery.*` trace event
(cat=resilience, so the run-report timeline shows it), the ambient
RunTelemetry's `recovery` list (machine-readable in run_summary.json),
and `self.timeline` for callers without telemetry.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from mmlspark_tpu.models.bundle import ModelBundle
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.numerics import DivergenceError, NonFiniteError
from mmlspark_tpu.observe.telemetry import active_run
from mmlspark_tpu.observe.trace import trace_event
from mmlspark_tpu.resilience.checkpoints import (latest_valid_checkpoint,
                                                 step_of)
from mmlspark_tpu.resilience.preemption import HungStepError, Preempted
from mmlspark_tpu.train.config import TrainerConfig
from mmlspark_tpu.train.trainer import Trainer


@dataclasses.dataclass
class RecoveryPolicy:
    """What the supervisor does between a failure and the retry.

    max_recoveries        restore-and-resume attempts before giving up
    skip_window_steps     size of the data window skipped, ending at the
                          failing step; None = skip everything since the
                          restore point (every step the probe had not yet
                          cleared is treated as suspect)
    refold_rng            fold the recovery count into the data-order RNG
                          so the retry shuffles different batches past
                          the restore point (TrainerConfig.rng_fold)
    lr_backoff            per-recovery learning-rate multiplier (1 = off)
    resume_on_preemption  resume in-process after Preempted instead of
                          re-raising (chaos drills / runner-less jobs)
    max_preemption_resumes  bound on those (preemptions never consume
                          the failure budget — capacity loss is not a
                          training pathology)
    """

    max_recoveries: int = 3
    skip_window_steps: Optional[int] = None
    refold_rng: bool = True
    lr_backoff: float = 1.0
    resume_on_preemption: bool = False
    max_preemption_resumes: int = 4


class RecoveryBudgetExceeded(RuntimeError):
    """The supervisor exhausted its recovery budget; the last failure is
    chained as __cause__ and `timeline` carries every decision made.
    The newest checkpoint on disk is still the last healthy state (the
    raise-before-write contract held on every attempt)."""

    def __init__(self, recoveries: int, timeline: list):
        self.recoveries = recoveries
        self.timeline = timeline
        super().__init__(
            f"recovery budget exhausted after {recoveries} "
            f"restore-and-resume attempt(s); the newest valid checkpoint "
            f"is the last healthy state — see .timeline for the full "
            f"recovery record")


class RecoverySupervisor:
    """Self-healing wrapper around Trainer.fit_arrays (module docstring).

        sup = RecoverySupervisor(cfg, RecoveryPolicy(max_recoveries=2))
        bundle = sup.fit_arrays(x, y, ckpt_dir="/ckpt")
        sup.timeline   # every failure / rollback / skip-window decision
    """

    def __init__(self, config: TrainerConfig,
                 policy: Optional[RecoveryPolicy] = None, mesh=None):
        self.config = config
        self.policy = policy or RecoveryPolicy()
        self._mesh = mesh
        self.timeline: list[dict] = []
        self.recoveries = 0
        self.preemption_resumes = 0
        self.trainer: Optional[Trainer] = None  # the current attempt's

    # -- timeline ---------------------------------------------------------
    def _record(self, event: str, **attrs) -> dict:
        rec = {"event": event, **attrs}
        self.timeline.append(rec)
        trace_event(f"recovery.{event}", cat="resilience", **attrs)
        run = active_run()
        if run is not None:
            run.record_recovery(rec)
        return rec

    # -- the supervised loop ----------------------------------------------
    def _attempt_config(self) -> TrainerConfig:
        cfg, pol = self.config, self.policy
        if self.recoveries == 0:
            return cfg
        lr = cfg.learning_rate * (pol.lr_backoff ** self.recoveries)
        return dataclasses.replace(
            cfg, learning_rate=lr,
            rng_fold=self.recoveries if pol.refold_rng else cfg.rng_fold)

    @staticmethod
    def _restore_step(ckpt_dir: str) -> int:
        path = latest_valid_checkpoint(ckpt_dir)
        if path is None:
            return 0
        try:
            return step_of(path.rsplit("/", 1)[-1])
        except ValueError:  # legacy single-file layout
            return 0

    def fit_arrays(self, x: np.ndarray, y: np.ndarray,
                   ckpt_dir: Optional[str] = None, resume: bool = False,
                   **fit_kw) -> ModelBundle:
        """Train with automatic rollback-recovery; returns the bundle of
        the attempt that completed.  Raises RecoveryBudgetExceeded when
        the policy's budget runs out, or re-raises Preempted when
        in-process preemption resume is not enabled."""
        cfg, pol = self.config, self.policy
        ckpt_dir = ckpt_dir if ckpt_dir is not None else cfg.checkpoint_dir
        if not ckpt_dir:
            raise ValueError(
                "RecoverySupervisor needs a checkpoint directory "
                "(ckpt_dir= or TrainerConfig.checkpoint_dir) — rollback "
                "recovery without a restore point is a restart")
        windows: list[tuple[int, int]] = []
        while True:
            trainer = Trainer(self._attempt_config(), mesh=self._mesh)
            self.trainer = trainer
            attempt_resume = resume or self.recoveries > 0 \
                or self.preemption_resumes > 0
            try:
                bundle = trainer.fit_arrays(
                    x, y, ckpt_dir=ckpt_dir, resume=attempt_resume,
                    skip_data_windows=windows or None, **fit_kw)
                self._record("completed",
                             steps=int(bundle.metadata.get("steps", 0)),
                             recoveries=self.recoveries,
                             preemption_resumes=self.preemption_resumes,
                             skipped_windows=len(windows))
                return bundle
            except NonFiniteError as e:
                failure, kind, fail_step = e, "nonfinite", e.step
            except DivergenceError as e:
                failure, kind, fail_step = e, "divergence", e.step
            except HungStepError as e:
                failure, kind, fail_step = e, "hung_step", e.step
            except Preempted as e:
                if not pol.resume_on_preemption:
                    self._record("preempted", step=e.step,
                                 resumed_in_process=False)
                    raise
                self.preemption_resumes += 1
                if self.preemption_resumes > pol.max_preemption_resumes:
                    self._record("gave_up", reason="preemption_budget",
                                 preemption_resumes=self.preemption_resumes
                                 - 1)
                    raise
                self._record("preempted", step=e.step,
                             resumed_in_process=True,
                             resume_no=self.preemption_resumes)
                continue  # capacity loss: resume, no failure budget spent
            # a training-health failure: roll back, apply policy, retry
            restore_step = self._restore_step(ckpt_dir)
            self.recoveries += 1
            inc_counter("recovery.failures")
            self._record("failure", kind=kind, step=fail_step,
                         restore_step=restore_step,
                         recovery=self.recoveries, detail=str(failure))
            if self.recoveries > pol.max_recoveries:
                self._record("gave_up", reason="recovery_budget",
                             recoveries=self.recoveries - 1,
                             budget=pol.max_recoveries)
                get_logger("train").error(
                    "recovery budget (%d) exhausted; newest valid "
                    "checkpoint in %s is the last healthy state",
                    pol.max_recoveries, ckpt_dir)
                raise RecoveryBudgetExceeded(
                    self.recoveries - 1, list(self.timeline)) from failure
            lo = restore_step if pol.skip_window_steps is None else \
                max(restore_step, fail_step - int(pol.skip_window_steps) + 1)
            windows.append((lo, fail_step))
            inc_counter("recovery.rollbacks")
            self._record(
                "recover", recovery=self.recoveries,
                restore_step=restore_step,
                skip_window=[lo, fail_step],
                lr_scale=round(pol.lr_backoff ** self.recoveries, 6),
                rng_fold=self.recoveries if pol.refold_rng else 0)
            get_logger("train").warning(
                "recovery %d/%d: %s at step %d — rolling back to step "
                "%d, skipping data window [%d, %d], resuming",
                self.recoveries, pol.max_recoveries, kind, fail_step,
                restore_step, lo, fail_step)
