"""In-process distributed trainer: optax + jit over a device mesh.

Replaces the reference's out-of-process training path — write CNTKText files,
generate BrainScript, `mpiexec -n <gpus> cntk configFile=...`
(CNTKLearner.scala:52-162, CommandBuilders.scala:60-93) — with a single
jit-compiled train step.  Parallelism is declarative:

  * data parallelism: batches sharded along the mesh 'data' axis; XLA inserts
    the gradient all-reduce over ICI (the MPI ring's replacement);
  * tensor parallelism: dense kernels' output dim sharded along 'model' when
    it divides evenly (new-design headroom beyond the reference, SURVEY 2b);
  * multi-host: the same code under jax.distributed (parallel/distributed.py).

Padding rows in the final minibatch are masked out of the loss — the
reference instead zero-padded and let garbage rows into the batch
(CNTKModel.scala:71-76); masking keeps loss gradients exact.  Pad rows are
filled by cycling real rows (never zeros) so stateful normalization layers
(BatchNorm) compute their batch statistics over real data; a partial final
batch therefore sees some rows duplicated in the statistics, which is the
standard drop-nothing tradeoff.
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.models.bundle import ModelBundle, _to_plain
from mmlspark_tpu.models.definitions import build_model
from mmlspark_tpu.observe import MetricData, get_logger
from mmlspark_tpu.observe.costmodel import capture_program_cost
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.numerics import (DivergenceError, LossSpikeDetector,
                                           NonFiniteError, tree_health)
from mmlspark_tpu.observe.spans import active_timings, monotonic, span_on
from mmlspark_tpu.observe.telemetry import active_run
from mmlspark_tpu.observe.trace import (active_tracer, current_span_id,
                                        span_on_tracer, trace_event,
                                        trace_span)
from mmlspark_tpu.parallel.bridge import (gather_replicated, gather_to_host,
                                          put_like, put_sharded, put_tree,
                                          put_tree_like, snapshot_tree)
from mmlspark_tpu.parallel.distributed import (barrier, initialize_distributed,
                                               is_coordinator, run_collective)
from mmlspark_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, batch_sharding, make_mesh, replicated
from mmlspark_tpu.parallel.partition import (UNMATCHED_REPLICATE,
                                             compatible_spec, leaf_spec,
                                             named_sharding, path_str,
                                             rules_to_json, use_mesh)
from mmlspark_tpu.data import Dataset
from mmlspark_tpu.resilience.chaos import get_injector
from mmlspark_tpu.resilience.checkpoints import (checkpoint_meta,
                                                 checkpoint_name,
                                                 latest_valid_checkpoint)
from mmlspark_tpu.resilience.ckpt_writer import (CheckpointWriter,
                                                 read_checkpoint)
from mmlspark_tpu.resilience.preemption import (HungStepError, Preempted,
                                                PreemptionGuard, StepWatchdog)
from mmlspark_tpu.train.config import TrainerConfig


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any  # {} for stateless models


def _param_sharding_rule(mesh, tensor_parallel: bool,
                         expert_parallel: bool = True,
                         partition_rules=None):
    """Map each param leaf to a sharding.  The partition-rule registry
    (parallel/partition.py) is consulted first: a leaf whose matched spec
    survives `compatible_spec` demotion gets the registry layout — the
    Megatron split for TransformerLM trees (column-parallel qkv/mlp_up/
    lm_head, row-parallel proj/mlp_down), expert stacks over 'model'.
    Leaves the registry replicates fall back to the legacy heuristics —
    EP for MoE expert stacks (ops/moe.py expert_parallel_rules folded
    into the product surface) and generic last-dim TP for wide dense
    kernels — so non-transformer architectures (ConvNet) keep their
    sharded training path unchanged."""
    model_size = mesh.shape.get(MODEL_AXIS, 1)

    from mmlspark_tpu.ops.moe import is_expert_stack
    from mmlspark_tpu.parallel.partition import DEFAULT_RULES
    rules = tuple(DEFAULT_RULES if partition_rules is None
                  else partition_rules)

    def rule(path, leaf: jax.ShapeDtypeStruct):
        shape = leaf.shape
        if tensor_parallel and model_size > 1:
            spec = compatible_spec(
                leaf_spec(path_str(path), shape, rules,
                          UNMATCHED_REPLICATE), shape, mesh)
            # expert_parallel=False must win over the registry's moe rule
            if len(spec) and (expert_parallel
                              or not is_expert_stack(path, shape,
                                                     model_size)):
                return named_sharding(mesh, spec)
        if (expert_parallel and model_size > 1
                and is_expert_stack(path, shape, model_size)):
            return named_sharding(mesh, P(MODEL_AXIS, None, None))
        if (tensor_parallel and model_size > 1 and len(shape) >= 2
                and shape[-1] % model_size == 0 and shape[-1] >= model_size * 8):
            spec = [None] * len(shape)
            spec[-1] = MODEL_AXIS
            return named_sharding(mesh, P(*spec))
        return replicated(mesh)

    return rule


def build_optimizer(cfg: TrainerConfig, total_steps: int,
                    learning_rate=None) -> optax.GradientTransformation:
    """The config's optax chain.  `learning_rate` overrides the config's
    base rate and may be a TRACED scalar — the population trainer
    (train/sweep.py) passes each sweep member's rate through `vmap`, so
    one compiled step trains N members at N different learning rates.
    The chain structure is identical either way, which is what makes a
    vmapped member's update arithmetic byte-compatible with a plain
    Trainer fit at the same rate."""
    base = cfg.learning_rate if learning_rate is None else learning_rate
    if cfg.lr_schedule == "constant":
        lr = base
    elif cfg.lr_schedule == "cosine":
        lr = optax.cosine_decay_schedule(base, max(total_steps, 1))
    elif cfg.lr_schedule == "warmup_cosine":
        lr = optax.warmup_cosine_decay_schedule(
            0.0, base, cfg.warmup_steps,
            max(total_steps, cfg.warmup_steps + 1))
    else:
        raise ValueError(f"unknown lr_schedule {cfg.lr_schedule}")
    if cfg.optimizer == "sgd":
        tx = optax.sgd(lr)
    elif cfg.optimizer == "momentum":
        tx = optax.sgd(lr, momentum=cfg.momentum)
    elif cfg.optimizer == "adam":
        tx = optax.adam(lr)
    else:
        tx = optax.adamw(lr, weight_decay=cfg.weight_decay)
    if cfg.optimizer != "adamw" and cfg.weight_decay:
        tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
    if cfg.gradient_clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(cfg.gradient_clip_norm), tx)
    return tx


def _make_loss(kind: str) -> Callable:
    def loss_fn(logits, labels, mask):
        mask = mask.astype(jnp.float32)
        denom = jnp.maximum(mask.sum(), 1.0)
        if kind == "softmax_xent":
            ll = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels.astype(jnp.int32))
        elif kind == "sigmoid_xent":
            ll = optax.sigmoid_binary_cross_entropy(
                logits.squeeze(-1), labels.astype(jnp.float32))
        elif kind == "mse":
            pred = logits.squeeze(-1) if logits.ndim > labels.ndim else logits
            ll = jnp.square(pred - labels.astype(jnp.float32))
        elif kind == "mae":
            pred = logits.squeeze(-1) if logits.ndim > labels.ndim else logits
            ll = jnp.abs(pred - labels.astype(jnp.float32))
        else:
            raise ValueError(f"unknown loss {kind}")
        if ll.ndim > 1:
            ll = ll.mean(axis=tuple(range(1, ll.ndim)))
        return (ll * mask).sum() / denom

    return loss_fn


def _fold_metrics(metrics_tree) -> dict:
    """Collapse a sown "metrics" collection (nested, one tuple entry per
    sow call) to {metric_name: mean scalar} — e.g. every MoE layer's
    overflow fraction averaged into one `moe_overflow_fraction` series.
    Runs under jit (static structure, scalar reductions only)."""
    grouped: dict = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(metrics_tree):
        name = next((p.key for p in reversed(path)
                     if hasattr(p, "key") and not str(p.key).isdigit()),
                    "metric")
        grouped.setdefault(str(name), []).append(
            jnp.asarray(leaf, jnp.float32).mean())
    return {k: jnp.stack(v).mean() for k, v in grouped.items()}


def _epoch_order(rng, epoch: int, n: int, n_local: int,
                 shuffle: bool) -> np.ndarray:
    """The `n` local row indices this epoch feeds, drawn from `n_local`
    available rows.  When partitions are unequal (n < n_local under
    multi-host lockstep), surplus rows are not dropped: shuffling samples
    the whole partition each epoch, and the unshuffled path rotates a
    window so every row participates within ceil(n_local/n) epochs."""
    if shuffle:
        return rng.permutation(n_local)[:n]
    if n == n_local:
        return np.arange(n)
    return (np.arange(n) + epoch * n) % n_local


class Trainer:
    """Drives the jit-compiled training loop for one model."""

    def __init__(self, config: TrainerConfig, mesh=None):
        self.config = config
        self.module = build_model(config.architecture, config.model_config)
        # wire up jax.distributed from env when launched multi-host (no-op
        # in the common single-process case); must precede mesh construction
        # so the mesh spans all hosts' devices
        initialize_distributed()
        self.mesh = mesh if mesh is not None else make_mesh(config.mesh)
        sig = inspect.signature(type(self.module).__call__)
        self._has_train_arg = "train" in sig.parameters
        self._loss = _make_loss(config.loss)
        self.history: list[dict] = []
        self._pp = config.pipeline_stages > 1
        # background checkpoint writers, one per directory (resilience/
        # ckpt_writer.py); created lazily, closed at the end of each fit
        self._writers: dict[str, CheckpointWriter] = {}
        self._effective_batch_size: Optional[int] = None
        if self._pp:
            self._validate_pipeline()

    def _validate_pipeline(self) -> None:
        """Pipeline parallelism preconditions, checked at construction so a
        bad config fails fast, not at the first compiled step."""
        cfg = self.config
        if cfg.architecture != "TransformerLM":
            raise ValueError(
                "pipeline_stages > 1 supports architecture='TransformerLM' "
                f"(got {cfg.architecture!r}); the stage schedule partitions "
                "a transformer block stack")
        m = self.module
        if m.attn_impl != "dense" or m.mlp_impl != "dense":
            raise ValueError(
                "pipeline training runs dense transformer blocks; compose "
                "long-context/MoE via attn_impl/mlp_impl WITHOUT "
                "pipeline_stages, or keep the pipelined model dense "
                f"(got attn_impl={m.attn_impl!r}, mlp_impl={m.mlp_impl!r})")
        if m.remat and m.remat_policy != "full":
            raise ValueError(
                "pipeline training supports remat_policy='full' only (the "
                "stage scan checkpoints whole layers); got "
                f"remat_policy={m.remat_policy!r}")
        stages = self.mesh.shape.get(MODEL_AXIS, 1)
        if stages != cfg.pipeline_stages:
            raise ValueError(
                f"pipeline_stages={cfg.pipeline_stages} must equal the "
                f"mesh's '{MODEL_AXIS}' axis size ({stages}) — the stage "
                "ring rides that axis")
        if m.n_layers % cfg.pipeline_stages:
            raise ValueError(
                f"n_layers={m.n_layers} must divide evenly into "
                f"pipeline_stages={cfg.pipeline_stages} stages")
        if cfg.pipeline_microbatches < 1:
            raise ValueError("pipeline_microbatches must be >= 1")

    # -- optimizer ------------------------------------------------------
    def _build_optimizer(self, total_steps: int) -> optax.GradientTransformation:
        return build_optimizer(self.config, total_steps)

    # -- state ----------------------------------------------------------
    def init_state(self, input_shape: tuple, total_steps: int = 1,
                   initial_bundle: Optional[ModelBundle] = None,
                   input_dtype=np.float32) -> TrainState:
        """Initialize (or warm-start, for fine-tuning) the sharded TrainState."""
        self._tx = self._build_optimizer(total_steps)
        if self._pp:
            return self._init_state_pipelined(initial_bundle)
        if initial_bundle is not None:
            variables = _to_plain(initial_bundle.variables)
        else:
            x = np.zeros(input_shape, input_dtype)
            variables = _to_plain(
                self.module.init(jax.random.key(self.config.seed), x))
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})

        rule = _param_sharding_rule(self.mesh, self.config.tensor_parallel,
                                    self.config.expert_parallel,
                                    getattr(self.config, "partition_rules",
                                            None))
        shardings = jax.tree_util.tree_map_with_path(
            lambda path, leaf: rule(
                path, jax.ShapeDtypeStruct(np.shape(leaf),
                                           np.asarray(leaf).dtype)),
            params)
        params = put_tree(params, shardings)
        batch_stats = put_tree(
            batch_stats, jax.tree_util.tree_map(
                lambda _: replicated(self.mesh), batch_stats))
        # opt_state leaves mirror params; EAGER init follows each param
        # leaf's NamedSharding (a jitted init commits the fresh zeros to
        # one device instead, leaving a mixed-device state that a later
        # checkpoint gather or post-restore step rejects)
        opt_state = self._tx.init(params)
        # warm starts resume the global step (bundle_from_state stamps it)
        # so checkpoint_every_steps boundaries align across fit() calls
        start = int((initial_bundle.metadata or {}).get("steps", 0)) \
            if initial_bundle is not None else 0
        return TrainState(step=jnp.asarray(start, jnp.int32), params=params,
                          opt_state=opt_state, batch_stats=batch_stats)

    # -- pipeline parallelism (pipeline_stages > 1) ----------------------
    def _init_state_pipelined(self, initial_bundle) -> TrainState:
        """TrainState whose params are the pipeline's stacked tree, block
        layers sharded over the stage ('model') axis.  Warm starts convert
        an ordinary TransformerLM bundle by stacking its blocks."""
        from mmlspark_tpu.parallel.pipeline import (
            init_pipelined_lm, pipeline_param_shardings,
            pipeline_params_from_variables)
        m = self.module
        if initial_bundle is not None:
            params = pipeline_params_from_variables(
                _to_plain(initial_bundle.variables), m.n_layers)
        else:
            params = init_pipelined_lm(
                jax.random.key(self.config.seed), vocab_size=m.vocab_size,
                d_model=m.d_model, n_heads=m.n_heads, n_layers=m.n_layers,
                max_len=m.max_len, mlp_ratio=m.mlp_ratio)
        params = put_tree(params, pipeline_param_shardings(self.mesh, params))
        # eager init: opt_state shardings mirror the stage-sharded params
        # (see init_state — jitted init would commit to one device)
        opt_state = self._tx.init(params)
        start = int((initial_bundle.metadata or {}).get("steps", 0)) \
            if initial_bundle is not None else 0
        return TrainState(step=jnp.asarray(start, jnp.int32), params=params,
                          opt_state=opt_state, batch_stats={})

    def _make_pipeline_train_step(self):
        from mmlspark_tpu.parallel.pipeline import pipelined_lm_apply
        mesh, m, cfg = self.mesh, self.module, self.config
        loss_fn, tx = self._loss, self._tx
        aux_w = float(cfg.aux_loss_weight)

        def train_step(state: TrainState, x, y, mask):
            def compute(params):
                logits = pipelined_lm_apply(
                    mesh, params, x, n_heads=m.n_heads,
                    n_micro=cfg.pipeline_microbatches,
                    stage_axis=MODEL_AXIS, mlp_ratio=m.mlp_ratio,
                    dtype=m.dtype, remat=m.remat)
                return loss_fn(logits, y, mask)

            loss, grads = jax.value_and_grad(compute)(state.params)
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt,
                                   batch_stats=state.batch_stats)
            return new_state, loss, {"grad_norm": optax.global_norm(grads)}

        del aux_w  # dense pipeline blocks sow no losses (validated in init)
        return jax.jit(train_step, donate_argnums=(0,))

    # -- the compiled step ----------------------------------------------
    def make_train_step(self):
        if self._pp:
            return self._make_pipeline_train_step()
        module, loss_fn = self.module, self._loss
        has_train = self._has_train_arg
        tx = self._tx
        mesh = self.mesh

        aux_w = float(self.config.aux_loss_weight)
        # numerics health (observe/numerics.py): when the probe cadence is
        # on, the step takes a traced `probe` flag and returns the health
        # dict under lax.cond — off-cadence steps pay one predicate, the
        # reductions only run on probe steps, and the step stays ONE
        # compiled program either way
        with_health = self.config.numerics_cadence > 0

        def train_step(state: TrainState, x, y, mask, probe=False):
            def compute(params):
                variables = {"params": params}
                if state.batch_stats:
                    variables["batch_stats"] = state.batch_stats
                if has_train:
                    out, mut = module.apply(
                        variables, x, train=True,
                        mutable=["batch_stats", "losses", "metrics"])
                    new_stats = mut.get("batch_stats", state.batch_stats)
                else:
                    out, mut = module.apply(variables, x,
                                            mutable=["losses", "metrics"])
                    new_stats = state.batch_stats
                loss = loss_fn(out, y, mask)
                if aux_w:
                    # model-sown auxiliary losses (e.g. the MoE
                    # load-balance term, ops/moe.py) join the objective
                    loss = loss + aux_w * sum(
                        jnp.asarray(v).sum() for v in
                        jax.tree_util.tree_leaves(mut.get("losses", {})))
                return loss, (new_stats,
                              _fold_metrics(mut.get("metrics", {})), out)

            (loss, (new_stats, metrics, logits)), grads = \
                jax.value_and_grad(compute, has_aux=True)(state.params)
            # the global gradient norm joins the per-step diagnostics (one
            # tree reduction under jit — noise next to the backward pass);
            # history gains a grad_norm column and telemetry step spans
            # carry it as an attr
            metrics = {**metrics, "grad_norm": optax.global_norm(grads)}
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt, batch_stats=new_stats)
            if with_health:
                def probed():
                    return tree_health(new_params, grads, updates,
                                       acts=logits)

                metrics["health"] = jax.lax.cond(
                    probe, probed,
                    lambda: {k: jnp.zeros((), jnp.float32)
                             for k in jax.eval_shape(probed)})
            return new_state, loss, metrics

        # `use_mesh` scopes the TRACE (the body runs inside jit tracing):
        # shard_constraint hints in the module forward (transformer heads
        # / MLP hidden, parallel/partition.py) bake this trainer's mesh
        # into the compiled step; on a 1-D mesh they are no-ops
        if not with_health:
            def plain_step(state, x, y, mask):
                with use_mesh(mesh):
                    return train_step(state, x, y, mask)
            return jax.jit(plain_step, donate_argnums=(0,))

        def meshed_step(state, x, y, mask, probe=False):
            with use_mesh(mesh):
                return train_step(state, x, y, mask, probe)
        return jax.jit(meshed_step, donate_argnums=(0,))

    # -- the loop --------------------------------------------------------
    def fit_arrays(self, x: np.ndarray, y: np.ndarray,
                   initial_bundle: Optional[ModelBundle] = None,
                   log_every: int = 50,
                   log_fn: Optional[Callable[[str], None]] = None,
                   ckpt_dir: Optional[str] = None,
                   resume: bool = False,
                   skip_data_windows: Optional[Sequence] = None
                   ) -> ModelBundle:
        """Train on arrays; under multi-host, `x`/`y` are this process's
        local data partition (the per-node data shard of the reference's
        MPI topology, CommandBuilders.scala:95-117) and each process
        contributes `batch_size / process_count` rows per global step via
        `put_sharded` — no host ever holds the global batch.

        Preemption safety (docs/resilience.md): `ckpt_dir` (default:
        config.checkpoint_dir) arms a SIGTERM guard — on preemption the
        in-flight step finishes, an emergency checkpoint is written, and
        `Preempted` is raised for the job runner to exit cleanly on.
        `resume=True` restarts from the newest VALID checkpoint in
        `ckpt_dir` (torn/corrupt files are skipped by checksum), replaying
        the same data order and skipping already-completed steps, so a
        preempted-and-resumed run finishes with the same step count as an
        uninterrupted one.

        Elastic resume: the checkpoint's `.meta.json` records the
        topology and EFFECTIVE batch size it was written under; a resume
        onto a different device count adopts the saved batch size (when
        it still divides the new data axis) so step numbering and data
        order replay identically, and restore re-commits the gathered
        full-shape arrays onto the new mesh's shardings (put_tree_like).

        `skip_data_windows` ([(first_step, last_step)] inclusive global
        executed-step ranges, normally supplied by the recovery
        supervisor) skips those steps' optimizer updates AND their data:
        the step counter advances (total step numbering is preserved —
        the loss-scaling "skip step" convention) but the offending
        window's batches are never staged or fed.
        """
        cfg = self.config
        ckpt_dir = ckpt_dir if ckpt_dir is not None else cfg.checkpoint_dir
        nproc = jax.process_count()
        n_local = len(x)
        n = n_local
        data_size = self.mesh.shape[DATA_AXIS]
        if nproc > 1:
            if data_size % nproc:
                raise ValueError(
                    f"multi-host training needs the data axis "
                    f"({data_size}) to be a multiple of the process count "
                    f"({nproc}); keep tensor/sequence parallelism within a "
                    "host (over ICI) and scale data parallelism across "
                    "hosts (over DCN)")
            # all processes must agree on the step count or the collectives
            # deadlock; each epoch feeds the smallest partition's row count,
            # but surplus rows on larger partitions ROTATE into later epochs
            # (epoch-order logic below) instead of being silently dropped
            from jax.experimental import multihost_utils
            sizes = multihost_utils.process_allgather(np.asarray(len(x)))
            n = int(sizes.min())
            if n != n_local:
                get_logger("train").warning(
                    "unequal data partitions %s: each epoch uses %d of this "
                    "process's %d rows (lockstep step count); surplus rows "
                    "rotate into later epochs", np.asarray(sizes).tolist(),
                    n, n_local)
            # save_checkpoint is a collective: every process must take the
            # checkpoint branches in lockstep or the job deadlocks
            flags = np.asarray([int(bool(ckpt_dir)),
                                int(cfg.checkpoint_every_steps or 0),
                                int(bool(resume))], np.int64)
            all_flags = multihost_utils.process_allgather(flags)
            if not (all_flags == flags).all():
                raise ValueError(
                    "checkpoint_dir/checkpoint_every_steps must be set "
                    "consistently on every process (checkpointing is a "
                    f"collective); got {all_flags.tolist()}")
        bs = cfg.batch_size
        bs = max(bs - bs % data_size, data_size)
        if self._pp:
            # each data-shard's local batch must split into whole
            # microbatches for the GPipe schedule
            unit = data_size * cfg.pipeline_microbatches
            bs = max(bs - bs % unit, unit)
        # elastic resume: a checkpoint written under a different device
        # count may have clamped a different effective batch size; adopt
        # the SAVED one (when it still divides the new data axis) so the
        # resumed run replays the identical step numbering and data order
        # the original fed.  Meta is read on the coordinator only — the
        # single-writer of the directory — and is advisory (missing meta
        # = no adjustment, pre-meta checkpoints keep restoring).
        if resume and ckpt_dir and is_coordinator():
            saved = checkpoint_meta(latest_valid_checkpoint(ckpt_dir)) or {}
            # mid-epoch data position saved by snapshot() ops: arm the
            # restore registry so the NEXT build of each tagged pipeline
            # fast-forwards past the already-consumed prefix
            if saved.get("data_snapshots"):
                from mmlspark_tpu.data.snapshot import set_restore_offsets
                set_restore_offsets(saved["data_snapshots"])
            saved_bs = int(saved.get("effective_batch_size") or 0)
            saved_dp = int(saved.get("data_devices") or 0)
            saved_mp = int(saved.get("model_devices") or 0)
            model_size = self.mesh.shape.get(MODEL_AXIS, 1)
            if saved_mp and self._pp and saved_mp != model_size:
                # the pipeline stage ring is NOT elastic: stage-sharded
                # block stacks cannot re-partition across a different
                # stage count mid-run
                raise ValueError(
                    f"checkpoint written under dp={saved_dp or '?'} x "
                    f"mp={saved_mp} cannot resume onto the current "
                    f"dp={data_size} x mp={model_size} mesh: pipeline "
                    f"training requires the same stage count "
                    f"(pipeline_stages == '{MODEL_AXIS}' axis size)")
            if saved_dp and (saved_dp != data_size
                             or (saved_mp and saved_mp != model_size)):
                trace_event("train.elastic_resume", cat="resilience",
                            saved_dp=saved_dp, dp=data_size,
                            saved_mp=saved_mp or 1, mp=model_size,
                            saved_batch=saved_bs or bs, batch=bs)
                inc_counter("train.elastic_resumes")
                get_logger("train").info(
                    "elastic resume: checkpoint written under dp=%d x "
                    "mp=%d, restoring onto dp=%d x mp=%d "
                    "(reshard-on-restore)", saved_dp, saved_mp or 1,
                    data_size, model_size)
            if saved_bs and saved_bs != bs:
                unit = data_size * (cfg.pipeline_microbatches
                                    if self._pp else 1)
                if saved_bs % unit:
                    raise ValueError(
                        f"elastic resume: checkpoint written under "
                        f"dp={saved_dp or '?'} x mp={saved_mp or 1} with "
                        f"effective batch size {saved_bs} cannot replay "
                        f"onto the current dp={data_size} x "
                        f"mp={model_size} mesh ({saved_bs} does not "
                        f"divide into the new data-axis unit {unit}); "
                        f"pick a batch_size divisible by both topologies "
                        f"to keep resumed runs reproducible")
                get_logger("train").info(
                    "elastic resume: adopting the checkpoint's effective "
                    "batch size %d (config clamped to %d) so data order "
                    "replays identically", saved_bs, bs)
                bs = saved_bs
        self._effective_batch_size = bs
        # rows this process feeds per global step; data_size % nproc == 0
        # and bs % data_size == 0 guarantee equal whole-row shares >= 1
        bs_local = bs // nproc
        steps_per_epoch = max(1, (n + bs_local - 1) // bs_local)
        total_steps = steps_per_epoch * cfg.epochs

        state = self.init_state((1,) + x.shape[1:], total_steps,
                                initial_bundle,
                                input_dtype=np.asarray(x).dtype)
        # the step numbering this run starts from (0, or the warm-start
        # bundle's recorded step); a resume checkpoint advances past it
        base_step = int(state.step)
        skip_until = base_step
        if resume and ckpt_dir:
            # every process must agree whether a restore happens (it is a
            # collective); the coordinator's directory decides
            found = int(latest_valid_checkpoint(ckpt_dir) is not None) \
                if is_coordinator() else 0
            if nproc > 1:
                from jax.experimental import multihost_utils
                found = int(run_collective(
                    "resume.poll", lambda: multihost_utils.
                    broadcast_one_to_all(np.asarray(found, np.int32))))
            if found:
                state = self.restore_checkpoint(state, ckpt_dir)
                skip_until = int(state.step)
                trace_event("train.resume", cat="resilience",
                            step=skip_until, ckpt_dir=ckpt_dir,
                            skipped_steps=skip_until - base_step)
                get_logger("train").info(
                    "resuming from checkpoint at step %d "
                    "(skipping %d completed steps)", skip_until,
                    skip_until - base_step)
        step_fn = self.make_train_step()
        x_sh = batch_sharding(self.mesh)

        # distinct per-process streams so partitions shuffle independently;
        # a nonzero rng_fold (recovery retries) folds the attempt number in
        # so the retry shuffles DIFFERENT batches past the restore point —
        # fold 0 keeps the historical stream byte-identical
        seed_key = cfg.seed + jax.process_index()
        rng = np.random.default_rng(
            seed_key if not cfg.rng_fold else [seed_key, int(cfg.rng_fold)])
        t0 = monotonic()
        # host-side counter seeded once from this run's base step so
        # checkpoint_every_steps boundaries stay aligned across fit()
        # calls; never sync on state.step mid-epoch.  On resume it replays
        # the original numbering, skipping steps below `skip_until` —
        # the epoch/batch order is identical, so the resumed run feeds
        # exactly the batches the preempted one never saw.
        chaos = get_injector()
        self._rows_seen = np.zeros(n_local, bool)  # coverage, inspectable
        # double-buffered staging (config.prefetch_depth, default 2): while
        # the jitted step k runs, the staging thread builds step k+1's
        # index/mask arrays and starts their device_put — the transfer
        # overlaps compute instead of alternating with it.  Numerics are
        # untouched: the plan below yields exactly the (epoch, step, batch)
        # sequence the serial loop fed, and rng consumption order is
        # identical (orders are drawn epoch-by-epoch on the consumer
        # thread as the staging window tops up).  The knob follows the
        # shared contract (parallel/prefetch.resolve_depth): positive
        # pins, 0 autotunes from the floor, -1 is fully serial.
        depth_knob = int(getattr(cfg, "prefetch_depth", 2))
        timings = active_timings()  # captured: workers have no context
        # telemetry (observe/trace.py): the tracer handle and the fit-level
        # span id are captured HERE on the consumer thread and passed into
        # the staging closure by value — the same capture-by-closure rule
        # as `timings` above, since worker threads never inherit contextvars
        tracer = active_tracer()
        run = active_run()  # the run's cost/gauge tables (same capture rule)
        fit_span = tracer.span(
            "train.fit", parent=current_span_id(), cat="phase",
            architecture=cfg.architecture, total_steps=total_steps,
            batch_size=bs, resume_from=skip_until - base_step or 0,
        ) if tracer is not None else None
        fit_id = fit_span.span_id if fit_span is not None else None
        # numerics health (observe/numerics.py): probe every `cadence`
        # executed steps; the loss-spike detector sees the probe steps'
        # losses; halt_on_nonfinite raises before any checkpoint write.
        # Detection granularity IS the cadence — keep it at or below
        # checkpoint_every_steps so a poisoned state cannot slip into a
        # rotation between probes.
        cadence = max(0, int(cfg.numerics_cadence)) if not self._pp else 0
        detector = LossSpikeDetector() if cadence else None
        self.last_health: Optional[dict] = None
        prog_key: Optional[str] = None
        # recovery skip windows (inclusive executed-step ranges): those
        # steps advance the counter but stage no data and run no update —
        # the supervisor's "skip the offending data window" lever
        windows = [(int(a), int(b)) for a, b in (skip_data_windows or [])]
        # hung-step watchdog: bounded-wait step execution (HungStepError
        # past the deadline; resilience/preemption.py)
        watchdog = StepWatchdog(cfg.step_timeout_s) \
            if cfg.step_timeout_s and not self._pp else None

        def _skipped(step_c: int) -> bool:
            return any(a <= step_c <= b for a, b in windows)

        def plan():
            step_c = base_step
            for epoch in range(cfg.epochs):
                order = _epoch_order(rng, epoch, n, n_local,
                                     cfg.shuffle_each_epoch)
                self._rows_seen[order] = True
                for start in range(0, n, bs_local):
                    if step_c < skip_until:  # completed before preemption
                        step_c += 1
                        continue
                    if _skipped(step_c):
                        # a recovery skip window: the marker (order=None)
                        # advances the step counter downstream, and the
                        # window's rows are never staged or transferred
                        yield (epoch, step_c, None, start)
                        step_c += 1
                        continue
                    yield (epoch, step_c, order, start)
                    step_c += 1

        def stage(item):
            epoch, step_c, order, start = item
            if order is None:  # skip-window marker: nothing to stage
                return epoch, step_c, None, None, None
            with span_on_tracer(tracer, "train.stage", parent=fit_id,
                                cat="stage", step=step_c):
                with span_on(timings, "host"):
                    idx = order[start:start + bs_local]
                    valid = len(idx)
                    if valid < bs_local:
                        # cycle real rows into the pad (module docstring)
                        idx = np.concatenate([idx,
                                              np.resize(order,
                                                        bs_local - valid)])
                    mask = np.zeros(bs_local, np.float32)
                    mask[:valid] = 1.0
                    xh, yh = x[idx], y[idx]
                with span_on(timings, "transfer"):
                    xb = put_sharded(xh, x_sh)
                    yb = put_sharded(yh, x_sh)
                    mask_d = put_sharded(mask, x_sh)
            return epoch, step_c, xb, yb, mask_d

        losses: list = []
        step_metrics: list = []
        cur_epoch: Optional[int] = None

        def finish_epoch():
            # one history row per epoch that executed at least one step
            # (epochs fully skipped by resume produce no staged items)
            if cur_epoch is None or not losses:
                return
            n_batches = len(losses)
            epoch_loss = float(np.sum(jax.device_get(losses)))
            rec = {"epoch": cur_epoch,
                   "loss": epoch_loss / max(n_batches, 1),
                   "wall_s": monotonic() - t0}
            if step_metrics:
                # model-sown diagnostics (e.g. MoE overflow fraction)
                # averaged over the epoch's steps, one history column each
                fetched = jax.device_get(step_metrics)
                for key in fetched[0]:
                    rec[key] = float(np.mean([m[key] for m in fetched]))
            self.history.append(rec)
            emit = log_fn if log_fn is not None \
                else get_logger("train").info
            if cur_epoch % max(1, log_every) == 0 \
                    or cur_epoch == cfg.epochs - 1:
                emit(f"epoch {cur_epoch}: loss={rec['loss']:.5f} "
                     f"({rec['wall_s']:.1f}s)")

        # NO `prefetch` op below the plan: its pulls must stay on the
        # consumer thread (rng orders are drawn as the map stage tops up)
        staged = (Dataset.from_iterable(plan)
                  .map(stage, name="train", depth=depth_knob, span=None)
                  .iterator())
        first_exec = True  # the first executed step pays the jit compile
        exec_count = 0     # watchdog warmup: see `dog` below
        with PreemptionGuard(install=bool(ckpt_dir)) as guard:
            try:
                for epoch, step_c, xb, yb, mask_d in staged:
                    if epoch != cur_epoch:
                        finish_epoch()
                        cur_epoch = epoch
                        losses, step_metrics = [], []
                    if xb is None:
                        # recovery skip window: the optimizer update and
                        # the window's data are skipped, but the step
                        # counter advances so total step numbering (and
                        # checkpoint naming) is preserved — the classic
                        # loss-scaling "skip step" convention
                        state = state.replace(step=state.step + 1)
                        inc_counter("train.skipped_steps")
                        trace_event("train.step_skipped", cat="resilience",
                                    step=step_c, epoch=epoch)
                        continue
                    chaos.on_step(step_c)  # may deliver simulated SIGTERM
                    if chaos.poison_nan(step_c):
                        # dtype-agnostic poison: a NaN loss mask drives
                        # the loss, gradients, and update non-finite —
                        # the numerics-probe drill
                        mask_d = mask_d * jnp.nan
                    probe_now = bool(cadence) and step_c % cadence == 0
                    step_args = (state, xb, yb, mask_d) + \
                        ((probe_now,) if cadence else ())
                    if prog_key is None:
                        prog_key = f"{tuple(xb.shape)}:{xb.dtype}"
                    if run is not None and first_exec:
                        # compile-time cost capture (observe/costmodel.py)
                        # BEFORE the first execution — the step donates
                        # its state, so lowering afterwards would see
                        # deleted buffers.  One AOT compile per run, and
                        # never a probe execution (donation).
                        capture_program_cost(step_fn, step_args,
                                             where="trainer",
                                             program=prog_key, run=run)

                    # watchdog warmup: the first execution pays the jit
                    # compile and the second may recompile at the
                    # donation/layout fixed point (the output state's
                    # layouts differ from eager init's) — both are
                    # legitimately slow, minutes on big models, so the
                    # step deadline arms from the third execution on
                    # (an early wedge is bounded by the collective
                    # timeouts instead)
                    dog = watchdog if exec_count >= 2 else None

                    def exec_step(args=step_args, step=step_c):
                        chaos.maybe_hang(step)  # hung-device drill hazard
                        out = step_fn(*args)
                        if dog is not None:
                            # the watchdog bounds a SYNCED execution: an
                            # async dispatch that never finishes must
                            # count as hung, not slip past the deadline
                            jax.block_until_ready(out)
                        return out

                    run_step = exec_step if dog is None else (
                        lambda: dog.run(exec_step, step=step_c,
                                        ckpt_dir=ckpt_dir))
                    if tracer is None:
                        with span_on(timings, "compute"):
                            state, loss, metrics = run_step()
                    else:
                        # per-step span: the scalar fetches force the step
                        # to FINISH inside the span, so its wall is the
                        # true step wall (the sync is the known, pinned
                        # cost of running with telemetry on)
                        with tracer.span(
                                "train.step", parent=fit_id, cat="step",
                                step=step_c, epoch=epoch,
                                first_step_compile=first_exec) as sp, \
                                span_on(timings, "compute"):
                            state, loss, metrics = run_step()
                            sp.attrs["loss"] = float(jax.device_get(loss))
                            if "grad_norm" in metrics:
                                sp.attrs["grad_norm"] = float(
                                    jax.device_get(metrics["grad_norm"]))
                            dur = sp.elapsed()
                            if dur > 0:
                                sp.attrs["rows_per_sec"] = round(
                                    bs_local / dur, 1)
                        if run is not None:
                            # synced step spans are true walls — the
                            # roofline joins them directly
                            run.add_program_time("trainer", prog_key, dur,
                                                 basis="step_wall")
                    first_exec = False
                    exec_count += 1
                    health = metrics.pop("health", None) if cadence else None
                    losses.append(loss)  # device array; fetched at epoch end
                    if metrics:
                        step_metrics.append(metrics)
                    if probe_now and health is not None:
                        # may raise NonFiniteError — BEFORE the
                        # step-boundary checkpoint below, so a poisoned
                        # state never rotates over the last finite one
                        self._numerics_check(step_c, loss, health,
                                             detector, run, ckpt_dir)
                    step = step_c + 1
                    if ckpt_dir and cfg.checkpoint_every_steps and \
                            step % cfg.checkpoint_every_steps == 0:
                        # async by default: the gather stays on this
                        # thread (collective), serialization + disk move
                        # to the writer thread (resilience/ckpt_writer.py)
                        self.save_checkpoint(state, ckpt_dir, step=step,
                                             sync=not cfg.async_checkpointing)
                    # the in-flight step finished; honor a pending SIGTERM
                    # at the step boundary (lockstep under multi-host:
                    # every process must agree before the collective save).
                    # The already-staged next batch is simply discarded —
                    # staged.close() below cancels the staging pool.
                    preempt_now = guard.triggered
                    if nproc > 1:
                        from jax.experimental import multihost_utils
                        preempt_now = bool(run_collective(
                            "preempt.sync", lambda: int(np.asarray(
                                multihost_utils.process_allgather(
                                    np.asarray(int(guard.triggered))))
                                .max())))
                    if preempt_now:
                        # emergency save is a BARRIER (sync=True): the
                        # checkpoint must be durable before the process
                        # exits on the preemption grace window
                        self.save_checkpoint(state, ckpt_dir, step=step,
                                             sync=True)
                        self._last_state = state
                        trace_event("train.preempted", cat="resilience",
                                    step=step, ckpt_dir=ckpt_dir)
                        raise Preempted(step=step, ckpt_dir=ckpt_dir)
                finish_epoch()
            except HungStepError:
                # the hung step never completed, so `state` is still the
                # last COMPLETED boundary state — write a best-effort
                # emergency checkpoint of it.  If the hung dispatch
                # already consumed (donated) the state's buffers, the
                # save fails and the rotation's newest periodic
                # checkpoint remains the restore point; either way the
                # abort is clean and a supervisor can resume.
                if ckpt_dir:
                    try:
                        path = self.save_checkpoint(state, ckpt_dir,
                                                    sync=True)
                        trace_event("train.hung_step_checkpoint",
                                    cat="resilience", path=path)
                    except Exception as e:
                        get_logger("train").warning(
                            "emergency checkpoint after hung step "
                            "failed (donated buffers?): %s", e)
                raise
            finally:
                staged.close()
                self._close_writers()
                if fit_span is not None:
                    fit_span.finish()
        if ckpt_dir:
            self.save_checkpoint(state, ckpt_dir, sync=True)
            self._close_writers()
        # the run's loss curve through the typed contract (Metrics.scala:37-47)
        self.training_metric_data().log("train", "debug")
        self._last_state = state  # inspectable (sharding asserts, resume)
        return self.bundle_from_state(state)

    def _numerics_check(self, step: int, loss, health: dict, detector,
                        run, ckpt_dir: Optional[str]) -> None:
        """One probe-step health pass (observe/numerics.py): fetch the
        jitted probe's scalars, feed the loss-spike detector, emit
        resilience-style events, and — with halt_on_nonfinite armed —
        raise NonFiniteError before any checkpoint write."""
        fetched = {k: float(v)
                   for k, v in jax.device_get(health).items()}
        loss_val = float(jax.device_get(loss))
        self.last_health = {"step": step, "loss": loss_val, **fetched}
        nonfinite = (fetched.get("nonfinite_params", 0.0)
                     + fetched.get("nonfinite_grads", 0.0)
                     + fetched.get("nonfinite_acts", 0.0)
                     + (0.0 if np.isfinite(loss_val) else 1.0))
        verdict = detector.update(loss_val) if detector is not None \
            else "ok"
        if run is not None:
            for key, value in fetched.items():
                run.gauge(f"numerics.{key}", value, step=step)
        trace_event("numerics.probe", cat="numerics", step=step,
                    loss=loss_val, verdict=verdict,
                    nonfinite_elements=nonfinite)
        if nonfinite:
            inc_counter("numerics.nonfinite_probes")
            trace_event("numerics.nonfinite", cat="resilience", step=step,
                        loss=loss_val, nonfinite_elements=nonfinite,
                        halting=bool(self.config.halt_on_nonfinite))
            get_logger("train").warning(
                "numerics: non-finite training state at step %d "
                "(%g element(s), loss=%g)", step, nonfinite, loss_val)
            if self.config.halt_on_nonfinite:
                raise NonFiniteError(
                    step, f"{nonfinite:g} non-finite element(s), "
                          f"loss={loss_val:g}", ckpt_dir)
        elif verdict in ("spike", "divergence"):
            inc_counter(f"numerics.loss_{verdict}")
            trace_event(f"numerics.loss_{verdict}", cat="resilience",
                        step=step, loss=loss_val,
                        threshold=detector.threshold())
            get_logger("train").warning(
                "numerics: loss %s at step %d (loss=%g, threshold=%g)",
                verdict, step, loss_val, detector.threshold())
            if verdict == "divergence" and self.config.halt_on_divergence:
                # same contract as NonFiniteError: raised BEFORE the
                # step-boundary checkpoint, so the newest checkpoint on
                # disk is the last pre-divergence state
                raise DivergenceError(step, loss_val,
                                      detector.threshold(), ckpt_dir)

    def training_metric_data(self) -> MetricData:
        """This trainer's history as a typed metric table (loss/wall plus
        any model-sown diagnostic columns, e.g. moe_overflow_fraction)."""
        extras = sorted({k for r in self.history for k in r}
                        - {"epoch", "loss", "wall_s"})
        cols = {key: [r.get(key, float("nan")) for r in self.history]
                for key in ("epoch", "loss", "wall_s", *extras)}
        return MetricData.create_table(
            cols, "training", self.config.architecture)

    def bundle_from_state(self, state: TrainState) -> ModelBundle:
        # collective under multi-host (gathers TP/EP/PP-sharded leaves);
        # every process gets the full bundle
        gathered = gather_to_host(state.params, self.mesh)
        if self._pp:
            # unstack the pipeline tree back into ordinary TransformerLM
            # variables: the bundle scores through TPUModel like any other
            from mmlspark_tpu.parallel.pipeline import (
                variables_from_pipeline_params)
            variables = variables_from_pipeline_params(
                gathered, self.module.n_layers)
        else:
            variables = {"params": gathered}
        if state.batch_stats:
            variables["batch_stats"] = gather_to_host(state.batch_stats,
                                                      self.mesh)
        # the bundle carries the layout it was trained under: the rule
        # set (JSON form, parallel/partition.py round-trip) and the mesh
        # shape, so scoring/decode re-shard the SAME way and a restore
        # onto a different dp x mp topology can name both in errors.
        # Arrays themselves are gathered full-shape — topology-portable.
        from mmlspark_tpu.parallel.partition import DEFAULT_RULES
        rules = getattr(self.config, "partition_rules", None) \
            or DEFAULT_RULES
        metadata = {
            "steps": int(state.step),
            "partition": {
                "rules": rules_to_json(rules),
                "mesh": {"data": int(self.mesh.shape.get(DATA_AXIS, 1)),
                         "model": int(self.mesh.shape.get(MODEL_AXIS, 1))},
            },
        }
        return ModelBundle.from_module(self.module, variables,
                                       metadata=metadata)

    # -- checkpoint / resume (absent in the reference; first-class here) --
    def _writer_for(self, ckpt_dir: str) -> CheckpointWriter:
        writer = self._writers.get(ckpt_dir)
        if writer is None:
            writer = self._writers[ckpt_dir] = CheckpointWriter(ckpt_dir)
        return writer

    def _close_writers(self) -> None:
        """Drain and stop every checkpoint writer (end-of-fit barrier);
        best-effort — a failed background write was already surfaced at
        its submit/drain, and a finally-block close must never mask the
        exception unwinding through it."""
        for writer in self._writers.values():
            writer.close(best_effort=True)
        self._writers.clear()

    def _ckpt_meta(self, step: int) -> dict:
        """The elastic-resume meta sidecar: the topology and EFFECTIVE
        batch size this checkpoint was written under, so a resume onto a
        different device count can replay the identical data order."""
        meta = {
            "step": int(step),
            "data_devices": int(self.mesh.shape.get(DATA_AXIS, 1)),
            "model_devices": int(self.mesh.shape.get(MODEL_AXIS, 1)),
            "process_count": int(jax.process_count()),
            "effective_batch_size": self._effective_batch_size,
            "seed": int(self.config.seed),
            "rng_fold": int(self.config.rng_fold),
            "format": 1,
        }
        # mid-epoch data position: every live snapshot() op's consumed
        # count rides the sidecar, so a resume replays exactly the
        # remaining elements (data/snapshot.py; docs/data-service.md)
        from mmlspark_tpu.data.snapshot import snapshot_offsets
        offsets = snapshot_offsets()
        if offsets:
            meta["data_snapshots"] = offsets
        return meta

    def save_checkpoint(self, state: TrainState, ckpt_dir: str, *,
                        step: Optional[int] = None,
                        sync: bool = True) -> str:
        """Write one rotation checkpoint (keep-last-K + LATEST pointer +
        sha256 sidecar + elastic meta, resilience/checkpoints.py).

        The gather is a collective under multi-host (it runs on every
        process, bounded by the collective timeout) but only the
        coordinator writes, so concurrent hosts sharing a filesystem
        never race.  The write itself rides the background writer
        (resilience/ckpt_writer.py): `sync=False` returns right after
        handing off the gathered device arrays (the step loop's async
        path — D2H + serialization + disk happen on the writer thread);
        `sync=True` drains first (emergency/final saves, external
        callers).  `step` supplies the host-known step so the async path
        never synchronizes on the device scalar."""
        with trace_span("checkpoint.save", cat="checkpoint", sync=sync):
            tree = {"step": state.step, "params": state.params,
                    "opt_state": state.opt_state,
                    "batch_stats": state.batch_stats}
            if jax.process_count() == 1:
                # every shard is addressable: a same-sharding snapshot
                # copy is the whole device-side cost (no n_devices-wide
                # replication) and protects the pending async write from
                # the next step's buffer donation; the writer assembles
                # shards during its device_get
                dev = snapshot_tree(tree)
            else:
                dev = run_collective(
                    "checkpoint.gather",
                    lambda: gather_replicated(tree, self.mesh))
            step = int(state.step) if step is None else int(step)
            if not is_coordinator():
                # the gather ran (collective); skip the D2H copy + write
                return os.path.join(ckpt_dir, checkpoint_name(step))
            return self._writer_for(ckpt_dir).submit(
                step, dev, meta=self._ckpt_meta(step), sync=sync)

    def restore_checkpoint(self, state: TrainState, ckpt_dir: str) -> TrainState:
        """Restore from the newest VALID checkpoint in the coordinator's
        `ckpt_dir` (checksum-validated; torn/corrupt files are skipped, a
        legacy single-file layout is accepted).  Under multi-host only the
        coordinator reads the file (matching coordinator-only writes — no
        shared filesystem required); values reach the other hosts via a
        broadcast collective, with a named barrier + bounded waits so a
        dead peer raises a diagnostic instead of hanging the job.

        Elastic by construction: the payload holds gathered full-shape
        arrays and the target layout comes from the LIVE state's
        shardings (`put_tree_like`), so a checkpoint saved under dp=N
        restores onto an M-device mesh with byte-identical weights."""
        with trace_span("checkpoint.restore", cat="checkpoint",
                        ckpt_dir=ckpt_dir):
            return self._restore_checkpoint(state, ckpt_dir)

    def _restore_checkpoint(self, state: TrainState,
                            ckpt_dir: str) -> TrainState:
        # deserialization needs only shapes/dtypes/structure — build the
        # template locally (no collectives, no D2H of live state); global
        # logical shapes are device-count-independent, which is what
        # makes the restore elastic
        template = jax.tree_util.tree_map(
            lambda a: np.zeros(np.shape(a), a.dtype),
            {"step": state.step, "params": state.params,
             "opt_state": state.opt_state, "batch_stats": state.batch_stats})
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            # all peers must be alive before committing to the broadcast:
            # the barrier converts a dead host into a CollectiveTimeoutError
            # naming this rendezvous, not an indefinite wedge
            barrier("restore_checkpoint")
            path = latest_valid_checkpoint(ckpt_dir) if is_coordinator() \
                else None
            # agree on readability first: if the coordinator raised while
            # the others sat in the broadcast collective, the job would
            # hang with no pointer to the cause
            readable = int(run_collective(
                "restore.readable", lambda: multihost_utils.
                broadcast_one_to_all(np.asarray(int(path is not None),
                                                np.int32))))
            if not readable:
                raise FileNotFoundError(
                    f"coordinator has no valid checkpoint in {ckpt_dir}")
            host = read_checkpoint(template, path) if is_coordinator() \
                else template
            restored = run_collective(
                "restore.broadcast",
                lambda: multihost_utils.broadcast_one_to_all(host))
        else:
            path = latest_valid_checkpoint(ckpt_dir)
            if path is None:
                raise FileNotFoundError(
                    f"no valid checkpoint in {ckpt_dir}")
            restored = read_checkpoint(template, path)
        # mesh= commits scalar leaves (step, optax counters) replicated on
        # the trainer's mesh rather than copying their single-device init
        # placement: when the mesh is a strict subset of the process's
        # devices (elastic resume onto fewer chips), a default-device
        # scalar would mix device sets inside the jitted train step
        return TrainState(
            step=put_like(jnp.asarray(restored["step"], jnp.int32),
                          state.step, mesh=self.mesh),
            params=put_tree_like(restored["params"], state.params,
                                 mesh=self.mesh),
            opt_state=put_tree_like(restored["opt_state"], state.opt_state,
                                    mesh=self.mesh),
            batch_stats=put_tree_like(restored["batch_stats"],
                                      state.batch_stats, mesh=self.mesh),
        )
