"""TrainerConfig: the typed replacement for BrainScript.

The reference configures CNTK training by generating BrainScript text files
(BrainscriptBuilder.scala:94-115) and shelling out to `cntk` under `mpiexec`
(CommandBuilders.scala:60-93).  Here training is in-process: a plain typed
config drives an optax/jit training loop, and "parallelTrain=true" becomes a
mesh spec.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from mmlspark_tpu.parallel.mesh import MeshSpec

LOSSES = ("softmax_xent", "sigmoid_xent", "mse", "mae")
OPTIMIZERS = ("sgd", "momentum", "adam", "adamw")


@dataclasses.dataclass
class TrainerConfig:
    # model
    architecture: str = "MLPClassifier"
    model_config: dict = dataclasses.field(default_factory=dict)

    # optimization (the BrainScript SGD block equivalent)
    optimizer: str = "momentum"
    learning_rate: float = 1e-2
    momentum: float = 0.9
    weight_decay: float = 0.0
    lr_schedule: str = "constant"          # constant | cosine | warmup_cosine
    warmup_steps: int = 0
    gradient_clip_norm: Optional[float] = None

    # loop
    loss: str = "softmax_xent"
    epochs: int = 1
    batch_size: int = 256
    seed: int = 0
    shuffle_each_epoch: bool = True

    # parallelism (replaces `mpiexec -n N` + parallelTrain)
    mesh: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    # shard dense kernels' last dim over the 'model' axis when it divides
    # evenly (simple tensor parallelism; data parallelism is always on)
    tensor_parallel: bool = True
    # shard MoE expert stacks' leading (E, ...) dim over 'model' (expert
    # parallelism; GSPMD places the all_to_all dispatch traffic)
    expert_parallel: bool = True
    # partition-rule registry override (parallel/partition.py): ordered
    # (regex-over-param-path, PartitionSpec) pairs, first match wins.
    # None = DEFAULT_RULES (the Megatron split for TransformerLM trees,
    # replication elsewhere — the generic wide-kernel heuristic still
    # applies to leaves the rules replicate).
    partition_rules: Optional[tuple] = None
    # GPipe pipeline parallelism over 'model' (TransformerLM only): the
    # block stack splits into this many stages, microbatches flow through
    # the ring (parallel/pipeline.py); 1 = off
    pipeline_stages: int = 1
    pipeline_microbatches: int = 4

    # input pipeline: staged batches in flight (data/dataset.py map stage)
    # — batch k+1's index/mask build and device_put overlap the jitted
    # step k (double buffering).  Positive pins the window, 0 hands it to
    # the data-layer Autotuner, -1 = synchronous staging on the dispatch
    # thread (the pre-autotuner meaning of 0)
    prefetch_depth: int = 2

    # checkpoint/resume (the reference had none, SURVEY section 5)
    checkpoint_dir: Optional[str] = None
    checkpoint_every_steps: int = 0        # 0 = only at end
    # background checkpoint writes (resilience/ckpt_writer.py): the step
    # loop only runs the device-side gather and hands the arrays off; the
    # D2H fetch + serialization + atomic write + rotation happen on the
    # writer thread, so checkpoint cadence stops costing step time.
    # Emergency (preemption/hang) and final saves always drain the writer
    # before returning.  False = every save drains immediately (the old
    # synchronous timing, same on-disk result).
    async_checkpointing: bool = True
    # hung-step watchdog (resilience/preemption.py StepWatchdog): bound
    # each step's wall time; past the deadline HungStepError is raised
    # after a best-effort emergency checkpoint, and a supervisor resumes
    # from the newest valid one.  Costs one worker-thread hop and a
    # block_until_ready per step while armed.  0 = off.
    step_timeout_s: float = 0.0
    # recovery: fold an attempt number into the data-order RNG so a
    # supervisor retry shuffles DIFFERENT batches after the restore point
    # (a data-dependent poison is not replayed step-for-step).  0 keeps
    # the historical stream byte-identical.
    rng_fold: int = 0

    # numerics health (observe/numerics.py): every `numerics_cadence`
    # steps the jitted probe reports non-finite counts and per-layer-group
    # grad/param/update-ratio norms, and the rolling loss-spike detector
    # sees that step's loss (0 = off).  Off-cadence steps pay only a
    # lax.cond predicate.  `halt_on_nonfinite` raises NonFiniteError at
    # the step boundary BEFORE any checkpoint write, so a poisoned state
    # never rotates over the last finite checkpoint.
    numerics_cadence: int = 50
    halt_on_nonfinite: bool = False
    # like halt_on_nonfinite, for the loss-spike detector's `divergence`
    # verdict: raise DivergenceError at the step boundary BEFORE any
    # checkpoint write, so the newest checkpoint stays pre-divergence
    halt_on_divergence: bool = False

    # weight on model-sown auxiliary losses (flax "losses" collection,
    # e.g. the MoE load-balance term); 0 ignores the sown values
    aux_loss_weight: float = 0.0

    def __post_init__(self):
        if self.loss not in LOSSES:
            raise ValueError(f"loss must be one of {LOSSES}, got {self.loss!r}")
        if self.optimizer not in OPTIMIZERS:
            raise ValueError(
                f"optimizer must be one of {OPTIMIZERS}, got {self.optimizer!r}")
        if isinstance(self.mesh, dict):
            self.mesh = MeshSpec(**self.mesh)
        if self.partition_rules is not None:
            # accept real (pattern, PartitionSpec) rules or the
            # rules_to_json wire form (lists of [pattern, entries])
            from jax.sharding import PartitionSpec
            from mmlspark_tpu.parallel.partition import rules_from_json
            rules = tuple(tuple(r) for r in self.partition_rules)
            if rules and not isinstance(rules[0][1], PartitionSpec):
                rules = rules_from_json(rules)
            self.partition_rules = rules

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["mesh"] = dataclasses.asdict(self.mesh)
        if self.partition_rules is not None:
            from mmlspark_tpu.parallel.partition import rules_to_json
            d["partition_rules"] = rules_to_json(self.partition_rules)
        return d

    @staticmethod
    def from_json(d: dict) -> "TrainerConfig":
        d = dict(d)
        if "mesh" in d:
            d["mesh"] = MeshSpec(**d["mesh"])
        if d.get("partition_rules") is not None:
            from mmlspark_tpu.parallel.partition import rules_from_json
            d["partition_rules"] = rules_from_json(d["partition_rules"])
        return TrainerConfig(**d)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1)

    @staticmethod
    def load(path: str) -> "TrainerConfig":
        with open(path) as f:
            return TrainerConfig.from_json(json.load(f))
