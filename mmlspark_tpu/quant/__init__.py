"""Quantized inference: int8/bf16 weights, int8 KV cache, accuracy gates.

`quantize_bundle` converts a trained ModelBundle offline; TPUModel scores
it via the fused wrappers in `modules.py` (registered per layer class in
utils/registry.py); `accuracy_gate` keeps every quantized arm honest.
KV-cache quantization lives behind `TextGenerator.kvCacheDtype`
(models/generate.py + ops/attention.py).  docs/performance.md has the
full design.
"""

from mmlspark_tpu.quant.gate import accuracy_gate
from mmlspark_tpu.quant.modules import (QuantConv, QuantDense,
                                        quant_conv_apply, quant_dense_apply,
                                        quantized_call)
from mmlspark_tpu.quant.quantize import (dequantize_array, dequantize_bundle,
                                         quantization_mode,
                                         quantize_array_int8, quantize_bundle,
                                         quantize_kv)

__all__ = [
    "QuantConv", "QuantDense", "accuracy_gate", "dequantize_array",
    "dequantize_bundle", "quant_conv_apply", "quant_dense_apply",
    "quantization_mode", "quantize_array_int8", "quantize_bundle",
    "quantize_kv", "quantized_call",
]
