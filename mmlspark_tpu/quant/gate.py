"""The quantization accuracy gate.

A quantized arm is only shippable next to its accuracy delta — a speedup
number without one is how silent quality regressions ship.  The gate runs
both models over the same table and pushes the predictions through the
full metadata-driven evaluator (`ml/statistics.classification_report`, the
ComputeModelStatistics protocol) three times:

  * baseline predictions vs true labels  -> baseline_accuracy
  * quantized predictions vs true labels -> quant_accuracy
  * quantized vs baseline predictions    -> agreement (top-1 match rate)

bench.py wires this next to every quantized arm's speedup (the cifar10
int8 line pins |accuracy_delta| <= 0.005 in tests/test_perf_floor.py).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.ml.statistics import classification_report


def _predictions(model, table: DataTable) -> np.ndarray:
    scored = model.transform(table)
    scores = np.asarray(scored[model.outputCol], np.float32)
    if scores.ndim != 2:
        raise ValueError(
            f"accuracy_gate needs 2-D class scores, got shape {scores.shape}")
    return np.argmax(scores, axis=1)


def accuracy_gate(baseline_model, quant_model, table: DataTable,
                  labels) -> dict:
    """Score `table` through both models; return the gate record.

    Both models must be scoring Transformers (TPUModel-shaped: an
    `outputCol` of per-class scores).  Returns::

        {"baseline_accuracy", "quant_accuracy", "accuracy_delta",
         "agreement", "n_rows"}

    accuracy_delta = quant - baseline (negative means the quantized model
    lost accuracy).
    """
    y = np.asarray(labels)
    pred_base = _predictions(baseline_model, table)
    pred_quant = _predictions(quant_model, table)
    acc_base = float(
        classification_report(y, pred_base).metrics["accuracy"][0])
    acc_quant = float(
        classification_report(y, pred_quant).metrics["accuracy"][0])
    agreement = float(
        classification_report(pred_base, pred_quant).metrics["accuracy"][0])
    return {
        "baseline_accuracy": round(acc_base, 4),
        "quant_accuracy": round(acc_quant, 4),
        "accuracy_delta": round(acc_quant - acc_base, 4),
        "agreement": round(agreement, 4),
        "n_rows": int(len(y)),
    }
