"""Post-training quantization of model bundles.

The serving-stack answer to "every inference path computes in float32":
weights are quantized ONCE, offline, and the compiled forward consumes them
directly — int8 weights stay int8 in HBM (and over the host->HBM link, a
4x byte reduction), and the dequantization is part of the jitted program,
fused by XLA into the weight read / matmul epilogue rather than ever
materializing a float copy in HBM.

Two modes (`quantize_bundle`):

  * ``bf16`` — cast the whole variable tree to bfloat16 and set the
    module's compute dtype to bfloat16: half the weight bytes, full MXU
    bf16 rates, no extra machinery.  The standard TPU serving dtype.
  * ``int8`` — per-output-channel symmetric int8 for every dense/conv
    ``kernel`` leaf (GPTQ-class weight-only PTQ): the int8 tensor replaces
    the kernel and a float32 ``kernel_scale`` vector (one scale per output
    channel) is stored alongside; norms, biases, embeddings, and MoE
    expert kernels stay bfloat16.  The forward runs int8 weights x bf16
    activations with the per-channel rescale applied AFTER the matmul
    (quant/modules.py) — int8 -> bf16 conversion is exact (|q| <= 127 fits
    bf16's mantissa), so the fused form loses nothing over
    dequantize-then-matmul and skips the float weight copy entirely.

Layout contract (what tests/test_quant.py pins byte-exactly through
save_bundle/load_bundle):

    {"kernel": int8 (..., out), "kernel_scale": float32 (out,), ...}

Tensor parallelism composes with this layout through the partition-rule
registry (parallel/partition.py): a ``*_scale`` leaf follows its kernel's
OUTPUT-channel spec — a column-parallel kernel (P(None, 'model')) shards
its (out,) scales over 'model' alongside it, a row-parallel kernel
(P('model', None)) replicates them — so an int8 bundle scores at mp >= 2
with no quant-specific placement code.

A leaf is quantized iff it is named ``kernel``, is floating, and has rank
2 (Dense) or 4 (2-D Conv); everything else floating becomes bfloat16.
The whole ``moe`` subtree (expert stacks AND router, ops/moe.py)
deliberately does NOT int8-quantize — decode re-applies the real MoEMLP
module against the raw tree (models/generate.py::_mlp) and must keep
seeing plain float kernels.

KV-cache quantization (`quantize_kv`) is the activation-side counterpart:
per-head symmetric int8, quantize-on-write inside the decode step, dequant
on read — on a single TPU device inside the fused Pallas kernel
(`ops/decode_attention.fused_single_query_attention`: k_scale applied
after QK^T, v_scale folded into the softmax weights, so the cache
streams as 1 byte/element with no dequantized copy ever materialized),
elsewhere inside the reference `ops/attention.single_query_attention`
with the identical algebraic hoist — models/generate.py wires it behind
`TextGenerator.kvCacheDtype`.
"""

from __future__ import annotations

import jax.numpy as jnp
import ml_dtypes
import numpy as np

from mmlspark_tpu.models.bundle import ModelBundle

INT8_MAX = 127.0

# per-channel clip search: candidate fractions of the channel's |w| max
# tried as the clipping range, best (minimum squared error) kept — the
# standard PTQ refinement (GPTQ/AWQ-family "clip search").  Shrinking the
# range below the outlier trades a large clip error on one weight for a
# finer step on all the others; on the trained cifar10 ConvNet this is
# the difference between an accuracy delta of -0.0056 and -0.0028.
_CLIP_FRACTIONS = (1.0, 0.975, 0.95, 0.925, 0.9, 0.85, 0.8)


def quantize_array_int8(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8 quantization of a kernel.

    The output channel is the LAST axis (flax Dense (in, out) and Conv
    HWIO both put it there).  Each channel's scale is chosen by an MSE
    clip search over `_CLIP_FRACTIONS` of the channel's |w| max; weights
    beyond the chosen range clip to +-127.  Returns (q int8, scale
    float32 (out,)) with w ~= q * scale and, per channel,
    |w - q*scale| <= max(scale/2, amax - 127*scale) (round-to-nearest
    inside the range, clip distance outside — test-pinned); all-zero
    channels get scale 0 (dequant reproduces the zeros exactly).
    """
    w = np.asarray(w, np.float32)
    red = tuple(range(w.ndim - 1))
    amax = np.abs(w).max(axis=red)
    best_scale = None
    best_err = None
    for frac in _CLIP_FRACTIONS:
        scale = amax * (frac / INT8_MAX)
        inv = np.where(scale > 0, 1.0 / np.where(scale > 0, scale, 1.0), 0.0)
        q = np.clip(np.round(w * inv), -INT8_MAX, INT8_MAX)
        err = ((w - q * scale) ** 2).sum(axis=red)
        if best_err is None:
            best_scale, best_err = scale, err
        else:
            better = err < best_err
            best_err = np.where(better, err, best_err)
            best_scale = np.where(better, scale, best_scale)
    scale = best_scale.astype(np.float32)
    inv = np.where(scale > 0, 1.0 / np.where(scale > 0, scale, 1.0), 0.0)
    q = np.clip(np.round(w * inv), -INT8_MAX, INT8_MAX).astype(np.int8)
    return q, scale


def dequantize_array(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """The float32 weights an int8 (q, scale) pair represents."""
    return np.asarray(q, np.float32) * np.asarray(scale, np.float32)


def _should_quantize(key: str, arr: np.ndarray) -> bool:
    return (key == "kernel" and arr.ndim in (2, 4)
            and np.issubdtype(arr.dtype, np.floating))


def _quantize_tree(tree: dict, mode: str, stats: dict,
                   int8_ok: bool = True) -> dict:
    out: dict = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            # the whole `moe` subtree stays float: decode re-applies the
            # real MoEMLP module against these params (generate.py::_mlp),
            # which must keep seeing plain kernels (router included)
            out[k] = _quantize_tree(v, mode, stats,
                                    int8_ok and k != "moe")
            continue
        arr = np.asarray(v)
        if mode == "int8" and int8_ok and _should_quantize(k, arr):
            q, s = quantize_array_int8(arr)
            out[k] = q
            out[k + "_scale"] = s
            stats["int8_kernels"] += 1
        elif np.issubdtype(arr.dtype, np.floating):
            out[k] = arr.astype(ml_dtypes.bfloat16)
        else:
            out[k] = arr
    return out


def _dequantize_tree(tree: dict, dtype=np.float32) -> dict:
    out: dict = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out[k] = _dequantize_tree(v, dtype)
        elif k.endswith("_scale") and k[:-len("_scale")] in tree:
            continue
        elif k + "_scale" in tree:
            out[k] = dequantize_array(v, tree[k + "_scale"]).astype(dtype)
        elif np.issubdtype(np.asarray(v).dtype, np.floating):
            out[k] = np.asarray(v, dtype)
        else:
            out[k] = np.asarray(v)
    return out


def quantize_bundle(bundle: ModelBundle, mode: str = "bf16") -> ModelBundle:
    """A new ModelBundle with quantized variables (the input is untouched).

    The architecture name is unchanged — quantization is a storage/compute
    property recorded in ``metadata["quantization"]``, not a different
    model — and the config's compute dtype becomes bfloat16 (int8 weights
    score against bf16 activations; bf16 weights compute natively).
    save_bundle/load_bundle round-trip the quantized tree byte-exactly
    (dtypes and scale arrays persist through msgpack; test-pinned).
    """
    if mode not in ("bf16", "int8"):
        raise ValueError(f"unknown quantization mode '{mode}' (bf16 | int8)")
    import jax
    host_vars = jax.device_get(bundle.variables)
    stats = {"int8_kernels": 0}
    variables = _quantize_tree(host_vars, mode, stats)
    config = dict(bundle.config)
    module = bundle.module()
    if "dtype" in getattr(module, "__dataclass_fields__", {}):
        config["dtype"] = "bfloat16"
    metadata = dict(bundle.metadata or {})
    metadata["quantization"] = {
        "mode": mode, "compute_dtype": "bfloat16",
        "int8_kernels": stats["int8_kernels"],
    }
    return ModelBundle(bundle.architecture, config, variables, metadata)


def dequantize_bundle(bundle: ModelBundle, dtype=np.float32) -> ModelBundle:
    """Expand a quantized bundle back to plain float weights (diagnostics /
    error measurement — never the serving path)."""
    variables = _dequantize_tree(bundle.variables, dtype)
    config = dict(bundle.config)
    metadata = dict(bundle.metadata or {})
    metadata.pop("quantization", None)
    return ModelBundle(bundle.architecture, config, variables, metadata)


def quantization_mode(bundle: ModelBundle) -> str | None:
    """'bf16' / 'int8' for a quantized bundle, None otherwise."""
    return ((bundle.metadata or {}).get("quantization") or {}).get("mode")


# --------------------------------------------------------------------------
# KV-cache quantization (jnp: runs inside the jitted decode programs)
# --------------------------------------------------------------------------

def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-head symmetric int8 of a K/V slab (..., H, D) -> (q, scale).

    scale has shape (..., H): one scale per (row, slot, head) — the
    granularity the decode write produces (one new token's K/V per step)
    and the read dequantizes at zero extra bandwidth cost (the scale array
    is 1/D the payload).  All-zero vectors (never-written cache slots) get
    scale 0, so dequant reproduces exact zeros.
    """
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = amax / INT8_MAX
    inv = jnp.where(amax > 0, INT8_MAX / jnp.where(amax > 0, amax, 1.0), 0.0)
    q = jnp.clip(jnp.round(x32 * inv[..., None]), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), scale
