"""Quantized module wrappers: fused int8-weight forwards for flax layers.

The compute half of the quant subsystem (quant/quantize.py is the storage
half).  Two layers of API:

  * **Wrapper functions** (`quant_dense_apply`, `quant_conv_apply`) — the
    fused math for nn.Dense / nn.Conv, registered in
    `utils/registry.py::QUANT_MODULE_WRAPPERS`.  `quantized_call()` is a
    context manager (flax `intercept_methods`) under which ANY module
    whose param dict carries the int8 layout ({kernel int8, kernel_scale
    f32}) routes through its registered wrapper, while unquantized layers
    (norms, embeddings, MoE) run their ordinary `__call__` untouched.
    TPUModel wraps its compiled forward in it for int8 bundles, so every
    registered architecture scores quantized without a re-export.
  * **Standalone modules** (`QuantDense`, `QuantConv`) — the same math as
    first-class flax modules owning int8 params, for models BUILT
    quantized rather than converted.

The fused form: y = (x_bf16 @ W_int8.astype(bf16)) * scale + bias, with
float32 accumulation (`preferred_element_type`) and the per-output-channel
rescale applied AFTER the matmul/conv — int8 -> bf16 conversion is exact,
so this is numerically at least as good as dequantize-then-matmul and the
float weight copy never exists: HBM holds 1 byte per weight, the MXU eats
bf16, the epilogue multiply is one fused op per output channel.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from mmlspark_tpu.utils.registry import (quant_wrapper_for,
                                         register_quant_wrapper)


def _ntuple(v, n: int) -> tuple:
    if v is None:
        v = 1
    return (v,) * n if isinstance(v, int) else tuple(v)


def quant_dense_apply(mod: nn.Dense, x: jax.Array, kernel_q: jax.Array,
                      kernel_scale: jax.Array,
                      bias: Optional[jax.Array]) -> jax.Array:
    """nn.Dense with int8 weights: bf16 matmul, f32 accumulate, per-output-
    channel rescale in the epilogue."""
    dtype = mod.dtype or jnp.bfloat16
    y = jax.lax.dot_general(
        x.astype(dtype), kernel_q.astype(dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = y * kernel_scale
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)


def quant_conv_apply(mod: nn.Conv, x: jax.Array, kernel_q: jax.Array,
                     kernel_scale: jax.Array,
                     bias: Optional[jax.Array]) -> jax.Array:
    """nn.Conv (2-D, NHWC/HWIO) with int8 weights; conv is linear per
    output channel, so the per-channel rescale moves after the conv
    exactly as for Dense."""
    n_sp = kernel_q.ndim - 2
    if n_sp != 2:
        raise NotImplementedError(
            f"quantized conv supports 2-D kernels, got rank {kernel_q.ndim}")
    if _ntuple(mod.input_dilation, n_sp) != (1,) * n_sp:
        raise NotImplementedError(
            "quantized conv does not support input_dilation")
    padding = mod.padding
    if isinstance(padding, str):
        if padding.upper() not in ("SAME", "VALID"):
            raise NotImplementedError(
                f"quantized conv does not support padding='{padding}'")
        padding = padding.upper()
    else:
        padding = [tuple(p) for p in padding]
    dtype = mod.dtype or jnp.bfloat16
    dn = jax.lax.conv_dimension_numbers(
        x.shape, kernel_q.shape, ("NHWC", "HWIO", "NHWC"))
    y = jax.lax.conv_general_dilated(
        x.astype(dtype), kernel_q.astype(dtype),
        window_strides=_ntuple(mod.strides, n_sp),
        padding=padding,
        rhs_dilation=_ntuple(mod.kernel_dilation, n_sp),
        dimension_numbers=dn,
        feature_group_count=mod.feature_group_count,
        preferred_element_type=jnp.float32)
    y = y * kernel_scale
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)


register_quant_wrapper(nn.Dense, quant_dense_apply)
register_quant_wrapper(nn.Conv, quant_conv_apply)


def _quant_interceptor(next_fun, args, kwargs, context):
    """flax method interceptor: route layers whose params carry the int8
    layout through their registered wrapper; pass everything else through."""
    mod = context.module
    if context.method_name != "__call__" or mod.scope is None:
        return next_fun(*args, **kwargs)
    wrapper = quant_wrapper_for(type(mod))
    if wrapper is None or not mod.scope.has_variable("params", "kernel_scale"):
        return next_fun(*args, **kwargs)
    kernel_q = mod.scope.get_variable("params", "kernel")
    kernel_scale = mod.scope.get_variable("params", "kernel_scale")
    bias = (mod.scope.get_variable("params", "bias")
            if mod.scope.has_variable("params", "bias") else None)
    return wrapper(mod, args[0], kernel_q, kernel_scale, bias)


def quantized_call():
    """Context manager: inside it, `module.apply(quantized_vars, x)` runs
    registered layers' fused int8 forwards.  Trace-time only — wrap the
    apply INSIDE the jitted function, so the dequant belongs to the
    compiled program (weights stay int8 in HBM)."""
    return nn.intercept_methods(_quant_interceptor)


# --------------------------------------------------------------------------
# Standalone quantized layers (for models built quantized)
# --------------------------------------------------------------------------

class QuantDense(nn.Module):
    """A Dense layer whose stored weights ARE the int8 layout.

    Params: kernel int8 (in, features), kernel_scale f32 (features,),
    bias bf16 (features,).  Forward is `quant_dense_apply`'s math.  Init
    gives zero weights/unit scales — real values come from
    `quantize_array_int8` of a trained kernel.
    """

    features: int
    use_bias: bool = True
    dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel_q = self.param("kernel", nn.initializers.zeros,
                              (jnp.shape(x)[-1], self.features), jnp.int8)
        kernel_scale = self.param("kernel_scale", nn.initializers.ones,
                                  (self.features,), jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), jnp.bfloat16)
                if self.use_bias else None)
        return quant_dense_apply(
            nn.Dense(self.features, use_bias=self.use_bias, dtype=self.dtype),
            x, kernel_q, kernel_scale, bias)


class QuantConv(nn.Module):
    """A 2-D Conv layer whose stored weights ARE the int8 layout (HWIO
    kernel, per-output-channel scales); forward is `quant_conv_apply`."""

    features: int
    kernel_size: Sequence[int] = (3, 3)
    strides: Union[int, Sequence[int]] = 1
    padding: str = "SAME"
    use_bias: bool = True
    dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kshape = tuple(self.kernel_size) + (jnp.shape(x)[-1], self.features)
        kernel_q = self.param("kernel", nn.initializers.zeros,
                              kshape, jnp.int8)
        kernel_scale = self.param("kernel_scale", nn.initializers.ones,
                                  (self.features,), jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), jnp.bfloat16)
                if self.use_bias else None)
        return quant_conv_apply(
            nn.Conv(self.features, tuple(self.kernel_size),
                    strides=self.strides, padding=self.padding,
                    use_bias=self.use_bias, dtype=self.dtype),
            x, kernel_q, kernel_scale, bias)
