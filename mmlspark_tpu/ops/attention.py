"""Attention kernels: dense, ring (sequence-parallel), and Ulysses.

Long-context support is new-design headroom over the reference — it has no
sequence dimension at all (SURVEY §5: its longest input is one image row,
and the structural seam is the minibatcher, CNTKModel.scala:50-104).  A
TPU-native framework makes sequence/context parallelism first-class:

  * `attention`        — standard dense multi-head attention (one device's
                         whole sequence; XLA fuses QK^T -> softmax -> @V).
  * `single_query_attention` — one decode step's query against a KV-cache
                         *window* under an explicit per-row visibility mask;
                         the building block of the cache-windowed decode
                         engine (models/generate.py): cost scales with the
                         window it is handed, not the model's max_len.
  * `ring_attention`   — sequence sharded over a mesh axis; K/V blocks
                         rotate around the ring via ppermute while each
                         device accumulates online-softmax partials, so
                         peak memory is O(S_local) and the permute overlaps
                         the next block's matmuls.  Call under shard_map.
  * `ulysses_attention`— all-to-all alternative: swap the seq shard for a
                         head shard, run dense attention on full sequences
                         locally, swap back.  Fewer collective steps, needs
                         heads % axis_size == 0.  Call under shard_map.

Both parallel forms are numerically equivalent to `attention` (tested on a
virtual 8-device mesh, tests/test_seq_parallel.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = False,
              scale: Optional[float] = None,
              q_offset=0) -> jax.Array:
    """Dense multi-head attention.

    q, k, v: (B, S, H, D) -> (B, S, H, D).  bfloat16-friendly: softmax
    statistics stay in float32.  `q_offset` shifts the queries' global
    positions for causal masking when q is a slice of a longer sequence
    (the all-gather sequence-parallel fallback).
    """
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_len, k_len = s.shape[-2], s.shape[-1]
        q_pos = q_offset + jnp.arange(q_len)
        mask = q_pos[:, None] >= jnp.arange(k_len)[None, :]
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def single_query_attention(q: jax.Array, k_cache: jax.Array,
                           v_cache: jax.Array, visible: jax.Array,
                           scale: Optional[float] = None,
                           k_scale: Optional[jax.Array] = None,
                           v_scale: Optional[jax.Array] = None) -> jax.Array:
    """One decode step's query against a KV-cache window.

    q: (B, H, D) — the single new token's query per row.
    k_cache, v_cache: (B, L, H, D) — a *prefix window* of the full cache;
        the caller sizes L to the current occupancy (rounded up to a
        chunk), so per-step bandwidth scales with how much cache is
        actually written, not with the model's max_len.
    visible: (B, L) bool — True where the query may attend.  Per-row,
        because bucketed prompts leave per-row pad holes between each
        row's true prompt and the shared decode slots; masked slots get
        exactly zero weight (NEG_INF -> exp underflows to 0.0), so layout
        padding never changes the math.
    k_scale, v_scale: (B, L, H) float32 or None — per-(row, slot, head)
        dequant scales for an int8-quantized cache (quant/quantize.py
        `quantize_kv`).  The dequant is algebraically hoisted out of the
        cache read: K's scale multiplies the score row AFTER the QK^T
        einsum and V's folds into the softmax weights BEFORE the PV
        einsum, so the einsums stream the raw int8 bytes — per-step HBM
        traffic is 1 byte per cached element plus a 1/D-sized scale
        array, never a dequantized float copy.

    Accumulates QK^T and PV in float32 (the single-query step is
    bandwidth-bound — the extra precision is free; same discipline as the
    full-cache decode path).  Returns (B, H, D) float32.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * scale
    if k_scale is not None:
        s = s * k_scale.astype(jnp.float32).transpose(0, 2, 1)
    s = jnp.where(visible[:, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        w = w * v_scale.astype(jnp.float32).transpose(0, 2, 1)
    return jnp.einsum("bhl,blhd->bhd", w, v_cache.astype(jnp.float32))


def single_query_attention_stats(q: jax.Array, k_cache: jax.Array,
                                 v_cache: jax.Array, visible: jax.Array,
                                 scale: Optional[float] = None,
                                 k_scale: Optional[jax.Array] = None,
                                 v_scale: Optional[jax.Array] = None
                                 ) -> tuple:
    """`single_query_attention` stopped before the normalize: the online-
    softmax partial statistics of one cache SHARD, ready for a cross-shard
    merge (`merge_attention_stats`) — the seq-sharded decode read.

    Same contract as the reference for q/caches/visible/scales, but the
    window L here is one device's LOCAL slice of the cache (the `visible`
    mask is computed against global slot ids by the caller, so ownership
    is pure layout).  Returns float32 (acc (B, H, D), m (B, H), l (B, H)):
    `acc` is the exp-weighted V sum against the LOCAL max `m`, `l` the
    local normalizer.  A shard whose every slot is masked reports
    m = NEG_INF, l = 0, acc = 0 — the merge's correction weight zeroes it
    out exactly, so ragged occupancy across shards never skews the
    softmax.  int8 dequant scales compose unchanged: k_scale multiplies
    the score row AFTER QK^T and v_scale folds into the weights BEFORE
    the PV einsum, both strictly local operations.

    On one shard `merge_attention_stats(acc, m, l)` reduces to acc / l —
    the same statistics `single_query_attention`'s softmax computes, so
    the two paths agree to float32 rounding (test-pinned)."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * scale
    if k_scale is not None:
        s = s * k_scale.astype(jnp.float32).transpose(0, 2, 1)
    s = jnp.where(visible[:, None, :], s, NEG_INF)
    m = s.max(axis=-1)                                       # (B, H)
    safe_m = jnp.where(m == NEG_INF, 0.0, m)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(s == NEG_INF, 0.0, p)
    l = p.sum(axis=-1)
    if v_scale is not None:
        p = p * v_scale.astype(jnp.float32).transpose(0, 2, 1)
    acc = jnp.einsum("bhl,blhd->bhd", p, v_cache.astype(jnp.float32))
    return acc, m, l


def merge_attention_stats(acc: jax.Array, m: jax.Array, l: jax.Array,
                          axis_name: Optional[str] = None) -> jax.Array:
    """The collective epilogue of a seq-sharded decode step: rescale each
    shard's partial (acc, m, l) statistics to the GLOBAL running max and
    reduce — one pmax plus one psum-pair of (B, H)-sized exchanges, the
    only cross-chip traffic the sharded cache read costs.

    With `axis_name=None` (single shard, tests) the same algebra runs
    without collectives: out = acc / l with the zero-row guard.  The
    rescale is exactly the flash/ring fold's correction term, so merging
    N shards computes the same softmax the one-shard read would — a
    fully-masked shard (m = NEG_INF) contributes weight 0.  Returns
    (B, H, D) float32, the `single_query_attention` output contract."""
    if axis_name is not None:
        m_g = jax.lax.pmax(m, axis_name)
        safe_m = jnp.where(m_g == NEG_INF, 0.0, m_g)
        corr = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
        l = jax.lax.psum(l * corr, axis_name)
        acc = jax.lax.psum(acc * corr[..., None], axis_name)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return acc / l_safe[..., None]


def segment_cache_attention(q: jax.Array, k_cache: jax.Array,
                            v_cache: jax.Array, visible: jax.Array,
                            scale: Optional[float] = None,
                            k_scale: Optional[jax.Array] = None,
                            v_scale: Optional[jax.Array] = None
                            ) -> jax.Array:
    """A short token segment's queries against a KV-cache window — the
    multi-query generalization of `single_query_attention`, and the read
    the speculative-decoding verify forward runs (models/generate.py): the
    target model scores all k+1 drafted positions in ONE forward, so each
    query needs its own visibility row.

    q: (B, S, H, D) — S segment queries per row (S is small: the
        speculative draft length plus one).
    k_cache, v_cache: (B, L, H, D) — a prefix window of the cache, already
        containing the segment's own K/V (the caller writes before
        reading, exactly as the single-query step does).
    visible: (B, S, L) bool — per-QUERY visibility: query j of a row sees
        its true-prompt slots plus the decode slots up to and including
        its own write slot, so later drafted positions attend earlier ones
        but never themselves-plus-one.
    k_scale, v_scale: (B, L, H) float32 or None — int8-cache dequant
        scales, hoisted exactly as in `single_query_attention`: K's scale
        multiplies the score rows AFTER QK^T, V's folds into the softmax
        weights BEFORE PV, so both einsums stream raw int8 bytes.

    Float32 statistics throughout; returns (B, S, H, D) float32.  With
    S = 1 this is elementwise-identical math to `single_query_attention`
    (same contractions, same masking) — the property the speculative
    path's greedy byte-exactness rests on (test-pinned)."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bshd,blhd->bhsl", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * scale
    if k_scale is not None:
        s = s * k_scale.astype(jnp.float32).transpose(0, 2, 1)[:, :, None, :]
    s = jnp.where(visible[:, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        w = w * v_scale.astype(jnp.float32).transpose(0, 2, 1)[:, :, None, :]
    return jnp.einsum("bhsl,blhd->bshd", w, v_cache.astype(jnp.float32))


def _block_scores(q, k, scale):
    return jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale


def _ring_fold_loop(k, v, axis_name: str, axis_size, fold, accumulators):
    """The ring rotate/fold protocol shared by ring_attention and
    ring_flash_attention: axis_size-1 fold+rotate steps, then a final fold
    with no trailing ppermute (the last rotation's result would never be
    read — wasted ICI hops).  `fold(i, k_cur, v_cur, *accs) -> accs`."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(i, carry):
        k_cur, v_cur = carry[0], carry[1]
        accs = fold(i, k_cur, v_cur, *carry[2:])
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, *accs)

    carry = jax.lax.fori_loop(0, axis_size - 1, step, (k, v, *accumulators))
    return fold(axis_size - 1, carry[0], carry[1], *carry[2:])


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = False,
                   scale: Optional[float] = None) -> jax.Array:
    """Ring attention over a sharded sequence axis (must run under
    shard_map with `axis_name` in scope).

    q, k, v: (B, S_local, H, D), the local sequence shard.  Each ring step
    computes this device's queries against the currently-held K/V block,
    folds the result into online-softmax accumulators (running max M,
    normalizer L, weighted sum ACC), then rotates K/V one hop around the
    ring with ppermute.  After axis_size steps every query has seen every
    key.  Causal masking uses global positions derived from the block's
    origin device.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale_ = scale if scale is not None else d ** -0.5

    q_pos = my_idx * s_local + jnp.arange(s_local)          # global q positions

    # derive initial accumulators from q so they carry the same
    # varying-manual-axes type as the loop outputs (shard_map scan rule)
    acc0 = (q * 0).astype(jnp.float32)                       # (B,Sq,H,D)
    zero_bhs = (q[..., 0] * 0).astype(jnp.float32).transpose(0, 2, 1)
    m0 = zero_bhs + NEG_INF                                  # (B,H,Sq)
    l0 = zero_bhs

    def fold(i, k_cur, v_cur, acc, m, l):
        """Fold one K/V block into the online-softmax accumulators."""
        # the block held at step i originated on device (my_idx - i) mod n
        src = (my_idx - i) % axis_size
        s_scores = _block_scores(q, k_cur, scale_)           # (B,H,Sq,Sk)
        if causal:
            k_pos = src * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]          # (Sq, Sk)
            s_scores = jnp.where(mask[None, None], s_scores, NEG_INF)
        blk_max = s_scores.max(axis=-1)                      # (B,H,Sq)
        m_new = jnp.maximum(m, blk_max)
        # guard fully-masked blocks: exp(NEG_INF - NEG_INF) would be 1
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s_scores - safe_m[..., None])
        p = jnp.where(s_scores == NEG_INF, 0.0, p)
        correction = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
        l_new = l * correction + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_cur.dtype),
                        v_cur).astype(jnp.float32)
        acc_new = acc * correction.transpose(0, 2, 1)[..., None] + pv
        return acc_new, m_new, l_new

    acc, _, l = _ring_fold_loop(k, v, axis_name, axis_size, fold,
                                (acc0, m0, l0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_flash_forward(q, k, v, axis_name, causal, scale, block_q, block_k):
    """Ring flash forward: returns (out, lse) with lse the FULL-sequence
    log-sum-exp per query (B, S_local, H) — the backward's global softmax
    statistic."""
    from mmlspark_tpu.ops.flash_attention import flash_attention_with_lse

    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale_ = scale if scale is not None else d ** -0.5
    q_off = my_idx * s_local

    acc0 = (q * 0).astype(jnp.float32)                        # (B,S,H,D)
    lse0 = (q[..., 0] * 0).astype(jnp.float32) + NEG_INF      # (B,S,H)

    def fold(i, k_cur, v_cur, acc, lse):
        src = (my_idx - i) % axis_size
        o_i, lse_i = flash_attention_with_lse(
            q, k_cur, v_cur, causal=causal, scale=scale_,
            q_offset=q_off, k_offset=src * s_local,
            block_q=block_q, block_k=block_k)
        new_lse = jnp.logaddexp(lse, lse_i)
        w_old = jnp.where(lse <= NEG_INF, 0.0, jnp.exp(lse - new_lse))
        w_new = jnp.where(lse_i <= NEG_INF, 0.0, jnp.exp(lse_i - new_lse))
        acc = acc * w_old[..., None] + o_i.astype(jnp.float32) \
            * w_new[..., None]
        return acc, new_lse

    acc, lse = _ring_fold_loop(k, v, axis_name, axis_size, fold,
                               (acc0, lse0))
    return acc.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, causal: bool = False,
                         scale: Optional[float] = None,
                         block_q: int = 1024,
                         block_k: int = 1024) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the local block op
    (the published Ring Attention design): K/V shards rotate around the
    mesh axis while each device runs `flash_attention_with_lse` against
    the currently-held shard and merges the normalized partial outputs by
    their log-sum-exp residuals.  Peak memory is O(block_q x block_k) per
    core — both the sequence AND the per-device shard can exceed VMEM-era
    limits (plain `ring_attention` materializes S_local x S_local scores
    per fold).

    Differentiable: the custom VJP runs a second ring pass in which each
    dK/dV accumulator travels WITH its K/V shard (returning home after the
    full cycle) while every device folds its local `flash_block_grads`
    contribution against the forward's saved full-sequence LSE — the
    long-context TRAINING path, still O(block_q x block_k) peak memory.
    Call under shard_map with `axis_name` in scope.
    """
    out, _ = _ring_flash_forward(q, k, v, axis_name, causal, scale,
                                 block_q, block_k)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q, block_k):
    out, lse = _ring_flash_forward(q, k, v, axis_name, causal, scale,
                                   block_q, block_k)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, block_q, block_k, res, g):
    from mmlspark_tpu.ops.flash_attention import flash_block_grads

    q, k, v, out, lse = res
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale_ = scale if scale is not None else d ** -0.5
    q_off = my_idx * s_local
    # delta = rowsum(dO * O): global because O is the full-softmax output
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    dq0 = (q * 0).astype(jnp.float32)
    dk0 = (k * 0).astype(jnp.float32)
    dv0 = (v * 0).astype(jnp.float32)

    rot = lambda x: jax.lax.ppermute(x, axis_name, perm)

    def fold(i, k_cur, v_cur, dk_cur, dv_cur, dq):
        src = (my_idx - i) % axis_size
        dq_c, dk_c, dv_c = flash_block_grads(
            q, k_cur, v_cur, g, lse, delta, causal, scale_,
            q_offset=q_off, k_offset=src * s_local,
            block_q=block_q, block_k=block_k)
        return (dk_cur + dk_c.astype(jnp.float32),
                dv_cur + dv_c.astype(jnp.float32),
                dq + dq_c.astype(jnp.float32))

    def step(i, carry):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        dk_cur, dv_cur, dq = fold(i, k_cur, v_cur, dk_cur, dv_cur, dq)
        # dk/dv rotate WITH their k/v shard so each accumulated gradient
        # ends on the device owning the shard it grades
        return rot(k_cur), rot(v_cur), rot(dk_cur), rot(dv_cur), dq

    k_l, v_l, dk_l, dv_l, dq_l = jax.lax.fori_loop(
        0, axis_size - 1, step, (k, v, dk0, dv0, dq0))
    # final fold outside the loop: k/v have made their last useful hop, so
    # only dk/dv take one more ppermute home (the forward's
    # _ring_fold_loop trims the same dead hops)
    dk_l, dv_l, dq_fin = fold(axis_size - 1, k_l, v_l, dk_l, dv_l, dq_l)
    dk_fin, dv_fin = rot(dk_l), rot(dv_l)
    return (dq_fin.astype(q.dtype), dk_fin.astype(k.dtype),
            dv_fin.astype(v.dtype))


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = False,
                      scale: Optional[float] = None) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style), under
    shard_map.

    Input shards are (B, S_local, H, D); the all_to_all regroups to
    (B, S_full, H_local, D) — full sequences, a slice of heads — so plain
    dense attention runs locally; a second all_to_all restores the
    sequence shard.  Heads must divide the axis size.
    """
    axis_size = jax.lax.psum(1, axis_name)
    h = q.shape[2]
    if h % axis_size != 0:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by axis size ({axis_size})")
    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)
    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)
    out = attention(scatter_heads(q), scatter_heads(k), scatter_heads(v),
                    causal=causal, scale=scale)
    return gather_heads(out)
