"""Pallas fused single-query attention: the decode-step cache read.

`ops/attention.single_query_attention` is the XLA-composed reference: one
einsum for QK^T, a masked softmax, a second einsum for PV, with the int8
dequant hoisted to the score row (k_scale) and the softmax weights
(v_scale).  XLA runs that as separate HBM round trips — the score row and
the softmax weights are materialized between the two einsums, and for an
int8 cache the dequant scales are re-read per einsum.  Steady-state decode
is bandwidth-bound (bench_lm_decode's roofline attribution), so those
round trips are the whole per-step budget.

This module fuses the read: one kernel streams K/V blocks of the cache
window through VMEM, dequantizes in-registers (k_scale multiplies the
score row AFTER QK^T, v_scale folds into the softmax weights BEFORE PV —
the same algebraic hoist as the reference, so the int8 bytes are the only
cache traffic), and folds blocks with the online-softmax accumulators of
`ops/flash_attention.py`.  Semantics match `single_query_attention`
exactly: float32 statistics, per-row visibility mask, (B, H, D) float32
out.

Layout: the cache stays (B, L, H, D).  Rather than transposing to the
flash kernel's (B*H, L, D) — a full relayout of the window per decode
step, the exact traffic the kernel exists to avoid — the head axis is
folded into the lane dimension: blocks are (block_k, H*D) slices of the
contiguous (B, L, H*D) view, per-head score rows are produced by one MXU
matmul against a constant head-selector matrix (lane i of the cache
belongs to head i // D), and the softmax weights are expanded back through
its transpose.  Scores and statistics live in a 128-lane tile (one lane
per head, padded with NEG_INF), so H <= 128.

Off TPU, for window shapes that don't tile the blocks, or inside a
shard_map manual region, the wrapper falls back to the reference — the
engine's CPU tier-1 path exercises exactly that checked fallback, while
parity tests drive the kernel itself through the interpreter
(`interpret=True`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mmlspark_tpu.ops.attention import (NEG_INF, single_query_attention,
                                        single_query_attention_stats)
from mmlspark_tpu.ops.flash_attention import (_auto_interpret,
                                              _in_manual_region)

# scores/statistics tile width: one lane per head (head h of the decode
# query scores in lane h), padded to the TPU lane count with NEG_INF
_STATS_LANES = 128

_warned_fallbacks: set = set()


def _warn_reference_fallback(reason: str, b: int, l: int, block_k: int,
                             interpret: bool) -> None:
    """The reference path re-materializes the score row and softmax
    weights in HBM — silently taking it on a real TPU decode loop gives up
    the fused read this kernel exists for, so it must be visible.  Deduped
    per reason (a serving process cycles through many window widths);
    interpreter contexts are test/CPU and stay quiet."""
    if interpret or reason in _warned_fallbacks:
        return
    _warned_fallbacks.add(reason)
    from mmlspark_tpu.observe import get_logger
    get_logger("ops.decode").warning(
        "fused_single_query_attention (first seen at B=%d, L=%d, "
        "block_k=%d): %s — falling back to the XLA-composed reference "
        "read; warned once per reason", b, l, block_k, reason)


def _head_selector(n_heads: int, head_dim: int):
    """(LANES, H*D) constant: T[h, i] = 1 where lane i belongs to head h.

    One matrix serves both directions: contracting the folded lane axis
    (dim 1) turns a (block_k, H*D) elementwise product into per-head score
    rows; contracting the stats-lane axis (dim 0) expands per-head weights
    back onto the folded lanes.  Rows h >= n_heads are all zero, so the
    NEG_INF padding lanes of the stats tile never leak into the output."""
    hd = n_heads * head_dim
    heads = jax.lax.broadcasted_iota(jnp.int32, (_STATS_LANES, hd), 0)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (_STATS_LANES, hd), 1)
    return (heads == lanes // head_dim).astype(jnp.float32)


def _scale_pad(n_heads: int):
    """(H, LANES) constant placing a per-head dequant scale in its stats
    lane (pad lanes get 0 — harmless, their scores are NEG_INF-masked)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (n_heads, _STATS_LANES), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n_heads, _STATS_LANES), 1)
    return (rows == cols).astype(jnp.float32)


def _sqa_kernel(q_ref, k_ref, v_ref, vis_ref, ks_ref, vs_ref, out_refs,
                acc_ref, m_ref, l_ref, *, scale: float, n_heads: int,
                head_dim: int, block_k: int, emit_stats: bool = False):
    """One (batch row, k-block) grid step.

    The grid's inner dimension walks the window's K/V blocks; the
    online-softmax state (acc, running max m, normalizer l) persists in
    VMEM scratch across those steps (TPU grids execute minor-to-major on
    one core), so VMEM holds one K/V block at a time and the window is
    bounded by HBM, not VMEM.

    `out_refs` is `(o_ref,)` for the normalized read, or — with
    `emit_stats` — `(acc_out, m_out, l_out)`: the final block then writes
    the raw online-softmax statistics instead of dividing, for the
    seq-sharded decode's cross-chip merge
    (`ops/attention.merge_attention_stats`)."""
    j = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    sel = _head_selector(n_heads, head_dim)

    q = q_ref[0].astype(jnp.float32) * scale            # (1, H*D)
    kb = k_ref[0].astype(jnp.float32)                   # (block_k, H*D)
    vb = v_ref[0].astype(jnp.float32)
    # per-head scores: fold q into the lanes, reduce each head's D lanes
    # through the selector on the MXU -> one score lane per head
    s = jax.lax.dot_general(kb * q, sel, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if ks_ref is not None:
        # int8 dequant, k side: the per-(slot, head) scale multiplies the
        # score row AFTER QK^T — the dot streamed raw int8 bytes
        ks = ks_ref[0].astype(jnp.float32)              # (block_k, H)
        s = s * jax.lax.dot_general(ks, _scale_pad(n_heads),
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (block_k, _STATS_LANES), 1)
    s = jnp.where((vis_ref[0] > 0) & (lanes < n_heads), s, NEG_INF)

    m = m_ref[:][0:1]                                   # (1, LANES)
    l = l_ref[:][0:1]
    m_new = jnp.maximum(m, s.max(axis=0, keepdims=True))
    # fully-masked-lane guards (same algebra as the flash kernel's fold)
    safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
    p = jnp.where(s == NEG_INF, 0.0, jnp.exp(s - safe_m))
    corr = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
    l_new = l * corr + p.sum(axis=0, keepdims=True)
    w = p
    if vs_ref is not None:
        # int8 dequant, v side: fold the scale into the softmax weights
        # BEFORE PV, so that dot too streams raw int8 bytes
        vs = vs_ref[0].astype(jnp.float32)
        w = w * jax.lax.dot_general(vs, _scale_pad(n_heads),
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    # expand per-head weights back onto the folded lanes and accumulate
    w_exp = jax.lax.dot_general(w, sel, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    corr_exp = jax.lax.dot_general(corr, sel, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
    acc = acc_ref[:][0:1] * corr_exp + (w_exp * vb).sum(axis=0,
                                                        keepdims=True)
    # sublane-broadcast writes: scratch tiles are (8, lanes); every row
    # holds the same single-query state (sub-tile writes aren't supported)
    acc_ref[:] = jnp.broadcast_to(acc, acc_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _():
        if emit_stats:
            oa_ref, om_ref, ol_ref = out_refs
            oa_ref[0] = acc_ref[:][0:1].astype(oa_ref.dtype)
            om_ref[0] = m_ref[:][0:1].astype(om_ref.dtype)
            ol_ref[0] = l_ref[:][0:1].astype(ol_ref.dtype)
            return
        (o_ref,) = out_refs
        l_fin = l_ref[:][0:1]
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        l_exp = jax.lax.dot_general(l_safe, _head_selector(n_heads,
                                                           head_dim),
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
        # a fully-masked row (l == 0 in every lane) divides 0 by 1 -> 0;
        # l_exp of a pad lane is 0 only where acc is also 0
        l_exp = jnp.where(l_exp == 0.0, 1.0, l_exp)
        o_ref[0] = (acc_ref[:][0:1] / l_exp).astype(o_ref.dtype)


def _fused_forward(q, k_cache, v_cache, visible, scale, k_scale, v_scale,
                   block_k: int, interpret: bool,
                   emit_stats: bool = False):
    b, h, d = q.shape
    l = k_cache.shape[1]
    hd = h * d
    # contiguous head-fold views: no relayout of the cache window
    q3 = q.reshape(b, 1, hd)
    k3 = k_cache.reshape(b, l, hd)
    v3 = v_cache.reshape(b, l, hd)
    vis3 = visible.astype(jnp.int32).reshape(b, l, 1)
    quantized = k_scale is not None

    in_specs = [
        pl.BlockSpec((1, 1, hd), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, block_k, hd), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, block_k, hd), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, block_k, 1), lambda i, j: (i, j, 0)),
    ]
    args = [q3, k3, v3, vis3]
    if quantized:
        in_specs += [pl.BlockSpec((1, block_k, h), lambda i, j: (i, j, 0)),
                     pl.BlockSpec((1, block_k, h), lambda i, j: (i, j, 0))]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    n_out = 3 if emit_stats else 1

    def kernel(q_ref, k_ref, v_ref, vis_ref, *rest):
        if quantized:
            ks_ref, vs_ref, rest = rest[0], rest[1], rest[2:]
        else:
            ks_ref, vs_ref = None, None
        out_refs, (acc_ref, m_ref, l_ref) = rest[:n_out], rest[n_out:]
        _sqa_kernel(q_ref, k_ref, v_ref, vis_ref, ks_ref, vs_ref, out_refs,
                    acc_ref, m_ref, l_ref, scale=scale, n_heads=h,
                    head_dim=d, block_k=block_k, emit_stats=emit_stats)

    if emit_stats:
        # raw statistics: acc on the folded lanes, m/l one lane per head
        out_specs = [pl.BlockSpec((1, 1, hd), lambda i, j: (i, 0, 0)),
                     pl.BlockSpec((1, 1, _STATS_LANES),
                                  lambda i, j: (i, 0, 0)),
                     pl.BlockSpec((1, 1, _STATS_LANES),
                                  lambda i, j: (i, 0, 0))]
        out_shape = [jax.ShapeDtypeStruct((b, 1, hd), jnp.float32),
                     jax.ShapeDtypeStruct((b, 1, _STATS_LANES),
                                          jnp.float32),
                     jax.ShapeDtypeStruct((b, 1, _STATS_LANES),
                                          jnp.float32)]
    else:
        out_specs = pl.BlockSpec((1, 1, hd), lambda i, j: (i, 0, 0))
        out_shape = jax.ShapeDtypeStruct((b, 1, hd), jnp.float32)

    out = pl.pallas_call(
        kernel,
        grid=(b, l // block_k),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((8, hd), jnp.float32),            # acc (folded lanes)
            pltpu.VMEM((8, _STATS_LANES), jnp.float32),  # running max / head
            pltpu.VMEM((8, _STATS_LANES), jnp.float32),  # normalizer / head
        ],
        interpret=interpret,
    )(*args)
    if emit_stats:
        acc, m, lsum = out
        return (acc.reshape(b, h, d), m[:, 0, :h], lsum[:, 0, :h])
    return out.reshape(b, h, d)


def fused_single_query_attention(q: jax.Array, k_cache: jax.Array,
                                 v_cache: jax.Array, visible: jax.Array,
                                 scale: Optional[float] = None,
                                 k_scale: Optional[jax.Array] = None,
                                 v_scale: Optional[jax.Array] = None,
                                 *, block_k: int = 256,
                                 interpret: Optional[bool] = None
                                 ) -> jax.Array:
    """`single_query_attention` with a fused Pallas cache read on TPU.

    Same contract as the reference (q (B, H, D); caches (B, L, H, D); per
    row visibility (B, L); optional per-(row, slot, head) int8 dequant
    scales (B, L, H); returns (B, H, D) float32) and the same float32
    statistics, so the two agree to rounding — tests/test_decode_attention
    pins the parity per dtype, and scripts/lint.py requires that registry
    entry for any `pallas_call` site in ops/.

    `interpret=None` resolves by platform: real TPU compiles the kernel,
    anything else takes the reference path (the interpreter inside a
    decode scan would be pure overhead — tier-1 CPU runs cover the
    fallback).  `interpret=True` forces the kernel through the Pallas
    interpreter — the parity tests' mode.  Shapes that don't tile
    (window % block_k, sublane-tile violations on real TPU, H > 128,
    shard_map manual regions) fall back with a deduped warning.
    """
    b, h, d = q.shape
    l = k_cache.shape[1]
    scale_ = scale if scale is not None else d ** -0.5
    block_k = min(block_k, l)
    if interpret is None:
        if _auto_interpret():
            # no real TPU: the reference is the intended path (quiet)
            return single_query_attention(q, k_cache, v_cache, visible,
                                          scale_, k_scale, v_scale)
        interpret = False

    reason = None
    if _in_manual_region(q):
        reason = "shard_map manual region (the partitioner owns placement)"
    elif (k_scale is None) != (v_scale is None):
        reason = "mixed quantization (k_scale xor v_scale)"
    elif h > _STATS_LANES:
        reason = f"n_heads {h} exceeds the {_STATS_LANES}-lane stats tile"
    elif l % block_k:
        reason = (f"window {l} does not tile block_k {block_k} (round the "
                  "window to a block multiple or shrink block_k)")
    elif not interpret:
        # mosaic sublane tiles: (8, 128) f32 / (16, 128) bf16 / (32, 128)
        # int8 — the K/V block's sublane dim is block_k
        sub = {jnp.int8.dtype: 32, jnp.bfloat16.dtype: 16}.get(
            k_cache.dtype, 8)
        if block_k % sub:
            reason = (f"block_k {block_k} is not a multiple of the "
                      f"{k_cache.dtype} sublane tile ({sub})")
    if reason is not None:
        _warn_reference_fallback(reason, b, l, block_k, interpret)
        return single_query_attention(q, k_cache, v_cache, visible, scale_,
                                      k_scale, v_scale)
    return _fused_forward(q, k_cache, v_cache, visible, scale_, k_scale,
                          v_scale, block_k, interpret)


def fused_single_query_attention_stats(q: jax.Array, k_cache: jax.Array,
                                       v_cache: jax.Array,
                                       visible: jax.Array,
                                       scale: Optional[float] = None,
                                       k_scale: Optional[jax.Array] = None,
                                       v_scale: Optional[jax.Array] = None,
                                       *, block_k: int = 256,
                                       interpret: Optional[bool] = None):
    """`single_query_attention_stats` with the fused cache read on TPU.

    Identical streaming to `fused_single_query_attention`, but the final
    block writes the raw online-softmax statistics instead of normalizing:
    returns float32 `(acc (B, H, D), m (B, H), l (B, H))` — the local-shard
    triple `ops/attention.merge_attention_stats` combines across a
    seq-sharded KV cache (running max via pmax, rescaled normalizer and
    accumulator via psum).  A fully-masked row reports m == NEG_INF and
    l == 0, the merge identity.  Fallback ladder matches the normalized
    wrapper exactly, landing on the XLA-composed reference stats.
    """
    b, h, d = q.shape
    l = k_cache.shape[1]
    scale_ = scale if scale is not None else d ** -0.5
    block_k = min(block_k, l)
    if interpret is None:
        if _auto_interpret():
            return single_query_attention_stats(q, k_cache, v_cache,
                                                visible, scale_, k_scale,
                                                v_scale)
        interpret = False

    reason = None
    if _in_manual_region(q):
        reason = "shard_map manual region (the partitioner owns placement)"
    elif (k_scale is None) != (v_scale is None):
        reason = "mixed quantization (k_scale xor v_scale)"
    elif h > _STATS_LANES:
        reason = f"n_heads {h} exceeds the {_STATS_LANES}-lane stats tile"
    elif l % block_k:
        reason = (f"window {l} does not tile block_k {block_k} (round the "
                  "window to a block multiple or shrink block_k)")
    elif not interpret:
        sub = {jnp.int8.dtype: 32, jnp.bfloat16.dtype: 16}.get(
            k_cache.dtype, 8)
        if block_k % sub:
            reason = (f"block_k {block_k} is not a multiple of the "
                      f"{k_cache.dtype} sublane tile ({sub})")
    if reason is not None:
        _warn_reference_fallback(reason, b, l, block_k, interpret)
        return single_query_attention_stats(q, k_cache, v_cache, visible,
                                            scale_, k_scale, v_scale)
    return _fused_forward(q, k_cache, v_cache, visible, scale_, k_scale,
                          v_scale, block_k, interpret, emit_stats=True)


__all__ = ["fused_single_query_attention",
           "fused_single_query_attention_stats"]
