"""Mixture-of-Experts with expert parallelism (EP) over a mesh axis.

New-design headroom over the reference (which has no sparse/conditional
compute at all — SURVEY §2b): a Switch-style top-1 MoE MLP.  Expert
parallelism follows the GSPMD recipe rather than hand-written collectives:
the stacked expert weights (E, D, H) are sharded over a mesh axis
(`expert_parallel_rules`), the dispatched slot tensor (E, C, D) carries a
matching sharding constraint, and XLA inserts the all_to_all / all_gather
traffic — the "annotate shardings, let the compiler place collectives"
discipline the rest of the framework uses for TP/DP.

Design for XLA: everything is static-shape.  Routing uses the classic
dispatch/combine one-hot formulation (einsum-only — no gather/scatter, no
dynamic shapes), with a fixed per-expert capacity
`C = ceil(T / E * capacity_factor)`; tokens beyond an expert's capacity
are dropped (their residual stream passes through unchanged), exactly the
Switch Transformer discipline.  The load-balance auxiliary loss
`E * Σ_e f_e · p_e` is sown into the `"losses"` collection for training
loops to add (weighted) to the objective.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mmlspark_tpu.parallel.mesh import MODEL_AXIS


def top1_dispatch(router_logits: jax.Array, capacity: int):
    """(dispatch (T,E,C), combine (T,E,C), aux_loss) from router logits.

    float32 routing throughout (softmax statistics must not ride bf16).
    `dispatch` places each kept token in its expert's next free slot;
    `combine` additionally scales by the router gate, so
    `y = combine^T · expert(dispatch · x)` is the Switch forward.
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                   # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], 1)[:, 0]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (T,E)
    # position of each token within its expert's queue (first-come order,
    # the deterministic Switch tie-break)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0            # (T,E)
    within = (pos < capacity) & (pos >= 0)
    pos_oh = jax.nn.one_hot(pos.max(axis=-1).astype(jnp.int32), capacity,
                            dtype=jnp.float32)                 # (T,C)
    dispatch = (onehot * within)[:, :, None] * pos_oh[:, None, :]
    combine = dispatch * gate[:, None, None]
    f = onehot.mean(axis=0)                                    # (E,)
    p = probs.mean(axis=0)
    aux = e * jnp.sum(f * p)
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: router -> top-1 experts -> combine.

    `expert_axis` names the mesh axis the (E, ...) tensors shard over; it
    only places a `with_sharding_constraint` on the slot tensor (harmless
    outside jit/mesh contexts where it is a no-op on CPU tests), the
    weight shardings themselves come from `expert_parallel_rules`.
    """

    d_model: int
    n_experts: int = 8
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16
    expert_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, d = x.shape
        t = b * s
        e = self.n_experts
        h = self.mlp_ratio * self.d_model
        capacity = max(1, int(np.ceil(t / e * self.capacity_factor)))

        xf = x.reshape(t, d)
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32))
        dispatch, combine, aux = top1_dispatch(logits, capacity)
        self.sow("losses", "moe_aux_loss", aux)

        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (e, d, h), jnp.float32)
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (e, h, d), jnp.float32)

        slots = jnp.einsum("tec,td->ecd", dispatch,
                           xf.astype(jnp.float32)).astype(self.dtype)
        if self.expert_axis is not None:
            try:
                from jax.sharding import PartitionSpec as P
                slots = jax.lax.with_sharding_constraint(
                    slots, P(self.expert_axis))
            except (ValueError, RuntimeError):
                pass  # no mesh in scope (eager CPU tests): constraint is moot
        hmid = nn.relu(jnp.einsum("ecd,edh->ech", slots,
                                  w_in.astype(self.dtype)))
        out = jnp.einsum("ech,ehd->ecd", hmid, w_out.astype(self.dtype))
        y = jnp.einsum("tec,ecd->td", combine, out.astype(jnp.float32))
        return y.astype(x.dtype).reshape(b, s, d)


def expert_parallel_rules(params: dict, mesh,
                          axis: str = MODEL_AXIS) -> dict:
    """NamedSharding tree for a param tree containing MoE experts: (E, ...)
    expert tensors shard their leading (expert) dim over `axis`; everything
    else replicates.  Feed to `jax.device_put` / `jit(in_shardings=...)` —
    XLA then places the EP all_to_all traffic (GSPMD).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def rule(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("w_in", "w_out") and leaf.ndim == 3:
            return NamedSharding(mesh, P(axis, None, None))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)
