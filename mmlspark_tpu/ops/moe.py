"""Mixture-of-Experts with expert parallelism (EP) over a mesh axis.

New-design headroom over the reference (which has no sparse/conditional
compute at all — SURVEY §2b): a Switch-style top-k MoE MLP.  Expert
parallelism follows the GSPMD recipe rather than hand-written collectives:
the stacked expert weights (E, D, H) are sharded over a mesh axis
(`expert_parallel_rules`), the dispatched slot tensor carries a matching
sharding constraint, and XLA inserts the all_to_all / all_gather traffic —
the "annotate shardings, let the compiler place collectives" discipline the
rest of the framework uses for TP/DP.

Design for XLA: everything is static-shape.  Routing uses the classic
dispatch/combine one-hot formulation (einsum-only — no gather/scatter, no
dynamic shapes) applied PER TOKEN GROUP, the Mesh-TF/GShard convention:
tokens are split into fixed groups of at most `group_size`, each group
routes independently with per-expert capacity
`C = ceil(G / E * capacity_factor * k)`, and tokens beyond an expert's
capacity within their group are dropped (their residual stream passes
through unchanged).  Grouping bounds the dispatch/combine tensors at
~`capacity_factor * k * T * group_size` float32 elements — LINEAR in the
token count T, where ungrouped routing would cost
`capacity_factor * T^2` (multiple GB per layer at long-context scale).

Observability: the router sows three values —

  * `"losses" / "moe_aux_loss"`: the Switch load-balance term
    `E * Σ_e f_e · p_e` (f = choice-1 dispatch frequency, p = mean router
    probability), to be weighted into the objective
    (TrainerConfig.aux_loss_weight);
  * `"losses" / "moe_z_loss"`: the router z-loss
    `z_loss_weight * mean(logsumexp(logits)^2)` — PRE-SCALED by
    `z_loss_weight` so the trainer's single aux_loss_weight knob applies
    to the sum of sown losses;
  * `"metrics" / "moe_overflow_fraction"`: the fraction of routing slots
    dropped by capacity this step, so capacity collapse is visible in
    training history instead of silently degrading quality.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mmlspark_tpu.parallel.mesh import MODEL_AXIS


def topk_dispatch(router_logits: jax.Array, capacity: int, k: int = 1):
    """(dispatch (T,E,C), combine (T,E,C), aux_loss, z_loss, kept_fraction)
    from one group's router logits (T, E).

    float32 routing throughout (softmax statistics must not ride bf16).
    All j-th choices queue behind every (j-1)-th choice in an expert's
    capacity buffer (the GShard priority rule); within a choice, slots
    fill in token order (the deterministic Switch tie-break).  `combine`
    scales by the router gate — raw for k=1 (Switch), normalized over the
    k chosen gates for k>1 (GShard) — so
    `y = combine^T · expert(dispatch · x)` is the MoE forward.
    """
    t, e = router_logits.shape
    logits32 = router_logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits32, axis=-1)

    remaining = probs
    counts = jnp.zeros((e,), jnp.float32)    # slots consumed per expert
    parts = []                               # (onehot, gate, pos_value)
    for _ in range(k):
        expert_idx = jnp.argmax(remaining, axis=-1)            # (T,)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        gate = jnp.take_along_axis(probs, expert_idx[:, None], 1)[:, 0]
        pos = (jnp.cumsum(onehot, axis=0) - 1.0 + counts[None, :])
        pos_val = (pos * onehot).sum(-1)                       # (T,)
        parts.append((onehot, gate, pos_val))
        counts = counts + onehot.sum(0)
        remaining = remaining * (1.0 - onehot)  # mask chosen for next choice

    if k > 1:
        denom = sum(g for _, g, _ in parts) + 1e-9
        parts = [(oh, g / denom, pv) for (oh, g, pv) in parts]

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    kept_slots = 0.0
    for onehot, gate, pos_val in parts:
        within = (pos_val < capacity) & (pos_val >= 0)
        pos_oh = jax.nn.one_hot(pos_val.astype(jnp.int32), capacity,
                                dtype=jnp.float32)
        d_j = (onehot * within[:, None])[:, :, None] * pos_oh[:, None, :]
        dispatch = dispatch + d_j
        combine = combine + d_j * gate[:, None, None]
        kept_slots = kept_slots + d_j.sum()

    # load balance on choice-1 frequencies (the Switch definition)
    f = parts[0][0].mean(axis=0)
    p = probs.mean(axis=0)
    aux = e * jnp.sum(f * p)
    z = jnp.mean(jax.scipy.special.logsumexp(logits32, axis=-1) ** 2)
    kept_fraction = kept_slots / float(t * k)
    return dispatch, combine, aux, z, kept_fraction


def top1_dispatch(router_logits: jax.Array, capacity: int):
    """(dispatch (T,E,C), combine (T,E,C), aux_loss): the Switch top-1
    special case of `topk_dispatch` (kept as the stable one-group API)."""
    dispatch, combine, aux, _, _ = topk_dispatch(router_logits, capacity, 1)
    return dispatch, combine, aux


def _group_size(t: int, target: int) -> int:
    """Largest divisor of t that is <= target (static Python arithmetic —
    shapes stay known to XLA)."""
    target = max(1, min(t, target))
    for g in range(target, 0, -1):
        if t % g == 0:
            return g
    return 1


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: router -> top-k experts -> combine.

    `expert_axis` names the mesh axis the (E, ...) tensors shard over; it
    only places a `with_sharding_constraint` on the slot tensor (harmless
    outside jit/mesh contexts where it is a no-op on CPU tests), the
    weight shardings themselves come from `expert_parallel_rules`.

    `group_size` caps the routing group (tokens route independently per
    group, GShard-style), bounding dispatch memory at
    ~capacity_factor * router_k * T * group_size floats.
    """

    d_model: int
    n_experts: int = 8
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16
    expert_axis: Optional[str] = None
    group_size: int = 512
    router_k: int = 1                  # 1 = Switch, 2 = GShard top-2
    z_loss_weight: float = 1e-3

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, d = x.shape
        t = b * s
        e = self.n_experts
        k = self.router_k
        h = self.mlp_ratio * self.d_model
        gs = _group_size(t, self.group_size)
        g = t // gs
        capacity = max(1, int(np.ceil(gs / e * self.capacity_factor * k)))

        xf = x.reshape(t, d)
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32))
        dispatch, combine, aux, z, kept = jax.vmap(
            lambda lg: topk_dispatch(lg, capacity, k))(
            logits.reshape(g, gs, e))
        self.sow("losses", "moe_aux_loss", aux.mean())
        self.sow("losses", "moe_z_loss", self.z_loss_weight * z.mean())
        self.sow("metrics", "moe_overflow_fraction", 1.0 - kept.mean())

        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (e, d, h), jnp.float32)
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (e, h, d), jnp.float32)

        xg = xf.reshape(g, gs, d).astype(jnp.float32)
        slots = jnp.einsum("gtec,gtd->egcd", dispatch, xg).astype(self.dtype)
        if self.expert_axis is not None:
            from mmlspark_tpu.parallel.partition import expert_constraint
            slots = expert_constraint(slots, self.expert_axis)
        hmid = nn.relu(jnp.einsum("egcd,edh->egch", slots,
                                  w_in.astype(self.dtype)))
        out = jnp.einsum("egch,ehd->egcd", hmid, w_out.astype(self.dtype))
        y = jnp.einsum("gtec,egcd->gtd", combine, out.astype(jnp.float32))
        return y.astype(x.dtype).reshape(b, s, d)


def is_expert_stack(path, shape, axis_size: int = 1) -> bool:
    """True when a param-tree leaf at `path` with `shape` is a stacked
    expert tensor whose leading (expert) dim can shard over an axis of
    `axis_size` devices.  The ONE predicate shared by
    `expert_parallel_rules` and the Trainer's sharding rule
    (train/trainer.py::_param_sharding_rule), so placement logic cannot
    diverge.  Scoped to leaves living under an MoE module (a path
    component containing "moe"), not bare `w_in`/`w_out` names — an
    unrelated module reusing those names must not get its leading dim
    split across the mesh; and the expert count must divide the axis or
    the leaf falls back to the caller's default placement.
    """
    keys = [p.key if hasattr(p, "key") else str(p) for p in path]
    return (len(shape) == 3
            and bool(keys) and keys[-1] in ("w_in", "w_out")
            and any("moe" in k.lower() for k in keys[:-1])
            and axis_size > 0 and shape[0] % axis_size == 0)


def expert_parallel_rules(params: dict, mesh,
                          axis: str = MODEL_AXIS) -> dict:
    """NamedSharding tree for a param tree containing MoE experts: (E, ...)
    expert tensors shard their leading (expert) dim over `axis`
    (`is_expert_stack` decides what qualifies); everything else
    replicates.  Feed to `jax.device_put` / `jit(in_shardings=...)` —
    XLA then places the EP all_to_all traffic (GSPMD).  Construction goes
    through parallel/partition.py (the sanctioned NamedSharding site).
    """
    from jax.sharding import PartitionSpec as P

    from mmlspark_tpu.parallel.partition import named_sharding

    axis_size = mesh.shape.get(axis, 1)

    def rule(path, leaf):
        if is_expert_stack(path, leaf.shape, axis_size):
            return named_sharding(mesh, P(axis, None, None))
        return named_sharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)
