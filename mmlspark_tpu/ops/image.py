"""Batched image ops: the OpenCV-JNI replacement.

The reference runs OpenCV C++ per row inside Spark UDFs — one JNI call per
image for resize/crop/cvtColor/blur/threshold/filter2D
(ImageTransformer.scala:28-154, applied at 272-304).  Here every op is a
batched XLA program over an HBM-resident (B, H, W, C) tensor: B images per
dispatch instead of one, fused by XLA, with reduce_window/conv lowering to
the TPU's vector/matrix units.

Conventions: NHWC layout, uint8 or float32 in [0, 255], BGR channel order
(the reference's OpenCV byte order, ImageSchema.scala:18-23).  All
functions are jit-compatible and shape-polymorphic only in B.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# OpenCV luma weights for BGR -> gray (cvtColor COLOR_BGR2GRAY)
_BGR_LUMA = (0.114, 0.587, 0.299)

# threshold types (Imgproc.THRESH_*)
THRESH_BINARY = "binary"
THRESH_BINARY_INV = "binary_inv"
THRESH_TRUNC = "trunc"
THRESH_TOZERO = "tozero"
THRESH_TOZERO_INV = "tozero_inv"


def _as_float(x: jax.Array) -> jax.Array:
    return x if jnp.issubdtype(x.dtype, jnp.floating) else x.astype(jnp.float32)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def resize(images: jax.Array, height: int, width: int,
           method: str = "linear") -> jax.Array:
    """Batched bilinear resize (OpenCV Imgproc.resize default INTER_LINEAR,
    ImageTransformer.scala:33-38)."""
    b, _, _, c = images.shape
    out = jax.image.resize(_as_float(images), (b, height, width, c), method)
    return out


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def crop(images: jax.Array, x: int, y: int, height: int, width: int) -> jax.Array:
    """Rectangle crop at (x, y) = (col, row), OpenCV Rect semantics
    (ImageTransformer.scala:47-58)."""
    return images[:, y:y + height, x:x + width, :]


@functools.partial(jax.jit, static_argnums=(1, 2))
def center_crop(images: jax.Array, height: int, width: int) -> jax.Array:
    h, w = images.shape[1], images.shape[2]
    y, x = max((h - height) // 2, 0), max((w - width) // 2, 0)
    return images[:, y:y + height, x:x + width, :]


@functools.partial(jax.jit, static_argnums=(1,))
def cvt_color(images: jax.Array, code: str) -> jax.Array:
    """Color conversion (Imgproc.cvtColor, ImageTransformer.scala:70-79).

    Codes: bgr2gray, rgb2gray, bgr2rgb, rgb2bgr, gray2bgr, gray2rgb.
    Gray output keeps a single channel axis.
    """
    x = _as_float(images)
    if code == "bgr2gray":
        w = jnp.asarray(_BGR_LUMA, x.dtype)
        return (x * w).sum(axis=-1, keepdims=True)
    if code == "rgb2gray":
        w = jnp.asarray(_BGR_LUMA[::-1], x.dtype)
        return (x * w).sum(axis=-1, keepdims=True)
    if code in ("bgr2rgb", "rgb2bgr"):
        return x[..., ::-1]
    if code in ("gray2bgr", "gray2rgb"):
        return jnp.repeat(x, 3, axis=-1)
    raise ValueError(f"unknown color conversion '{code}'")


@functools.partial(jax.jit, static_argnums=(1, 2))
def blur(images: jax.Array, height: int, width: int) -> jax.Array:
    """Normalized box blur (Imgproc.blur, ImageTransformer.scala:90-97).

    OpenCV anchors the kernel at its center with BORDER_REFLECT_101-ish
    edges; here edges use mean-of-valid (normalized same-padding), which
    matches in the interior.
    """
    x = _as_float(images)
    ones = jnp.ones_like(x)
    window = (1, height, width, 1)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window,
                                   (1, 1, 1, 1), "SAME")
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                   (1, 1, 1, 1), "SAME")
    return summed / counts


def gaussian_kernel_1d(size: int, sigma: float) -> np.ndarray:
    """OpenCV getGaussianKernel: ksize x 1 column kernel, normalized.
    sigma <= 0 uses OpenCV's auto rule 0.3*((ksize-1)*0.5 - 1) + 0.8."""
    if sigma <= 0:
        sigma = 0.3 * ((size - 1) * 0.5 - 1) + 0.8
    r = np.arange(size, dtype=np.float64) - (size - 1) / 2
    k = np.exp(-(r ** 2) / (2 * sigma ** 2))
    return (k / k.sum()).astype(np.float32)


@functools.partial(jax.jit, static_argnums=(1, 2))
def gaussian_kernel(images: jax.Array, aperture_size: int,
                    sigma: float) -> jax.Array:
    """The reference's gaussiankernel stage (ImageTransformer.scala:133-141):
    filter2D with the ksize x 1 column kernel — a VERTICAL 1-D gaussian."""
    x = _as_float(images)
    k = jnp.asarray(gaussian_kernel_1d(aperture_size, sigma))
    kernel = k.reshape(aperture_size, 1, 1, 1)  # HWIO, depthwise
    b, h, w, c = x.shape
    # depthwise conv: move channels into batch
    xc = x.transpose(0, 3, 1, 2).reshape(b * c, h, w, 1)
    out = jax.lax.conv_general_dilated(
        xc, kernel, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out.reshape(b, c, h, w).transpose(0, 2, 3, 1)


@functools.partial(jax.jit, static_argnums=(1, 2))
def gaussian_blur(images: jax.Array, size: int, sigma: float) -> jax.Array:
    """Full separable 2-D gaussian blur (beyond-reference convenience)."""
    tmp = gaussian_kernel(images, size, sigma)
    x = tmp.transpose(0, 2, 1, 3)  # swap H/W, reuse the vertical pass
    return gaussian_kernel(x, size, sigma).transpose(0, 2, 1, 3)


@functools.partial(jax.jit, static_argnums=(3,))
def threshold(images: jax.Array, thresh: float, max_val: float,
              kind: str = THRESH_BINARY) -> jax.Array:
    """Imgproc.threshold (ImageTransformer.scala:110-122)."""
    x = _as_float(images)
    above = x > thresh
    if kind == THRESH_BINARY:
        return jnp.where(above, max_val, 0.0)
    if kind == THRESH_BINARY_INV:
        return jnp.where(above, 0.0, max_val)
    if kind == THRESH_TRUNC:
        return jnp.minimum(x, thresh)
    if kind == THRESH_TOZERO:
        return jnp.where(above, x, 0.0)
    if kind == THRESH_TOZERO_INV:
        return jnp.where(above, 0.0, x)
    raise ValueError(f"unknown threshold type '{kind}'")


@functools.partial(jax.jit, static_argnums=(1,))
def flip(images: jax.Array, code: int = 1) -> jax.Array:
    """OpenCV flip: 0 = vertical (around x-axis), >0 horizontal, <0 both."""
    if code == 0:
        return images[:, ::-1, :, :]
    if code > 0:
        return images[:, :, ::-1, :]
    return images[:, ::-1, ::-1, :]


@jax.jit
def unroll(images: jax.Array) -> jax.Array:
    """HWC -> flat CHW float vector per image.

    The reference's UnrollImage (UnrollImage.scala:18-42) reorders the
    OpenCV HWC bytes into CHW doubles — CNTK's expected layout — fixing
    signed-byte underflow on the way.  Batched: (B,H,W,C) -> (B, C*H*W)
    float32; uint8 inputs are widened (no sign fix needed, numpy bytes are
    already unsigned).
    """
    x = _as_float(images)
    b = x.shape[0]
    return x.transpose(0, 3, 1, 2).reshape(b, -1)


@jax.jit
def normalize(images: jax.Array, mean: Optional[jax.Array] = None,
              std: Optional[jax.Array] = None) -> jax.Array:
    """Scale [0,255] -> [0,1], then optional per-channel standardization."""
    x = _as_float(images) / 255.0
    if mean is not None:
        x = x - jnp.asarray(mean, x.dtype)
    if std is not None:
        x = x / jnp.asarray(std, x.dtype)
    return x
