"""Batched device kernels (image ops, attention)."""

from mmlspark_tpu.ops import image

__all__ = ["image"]
