"""Batched device kernels (image ops, attention — dense, ring/Ulysses
sequence-parallel, and the Pallas flash kernel).

The flash kernel is NOT re-exported here: `mmlspark_tpu.ops.flash_attention`
is the submodule (import the function from it), and importing it pulls
jax.experimental.pallas + its TPU backend — a measurably slow import that
dense/image-only users should never pay.  A lazy __getattr__ re-export
would be permanently shadowed by the submodule object the first time
anything imports it, resolving to a module or a function depending on
process-wide import order.
"""

from mmlspark_tpu.ops import image
from mmlspark_tpu.ops.attention import (attention, ring_attention,
                                        ulysses_attention)

__all__ = ["image", "attention", "ring_attention", "ulysses_attention"]
