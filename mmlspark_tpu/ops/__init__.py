"""Batched device kernels (image ops, attention — dense, ring/Ulysses
sequence-parallel, and the Pallas flash kernel)."""

from mmlspark_tpu.ops import image
from mmlspark_tpu.ops.attention import (attention, ring_attention,
                                        ulysses_attention)

__all__ = ["image", "attention", "ring_attention", "ulysses_attention",
           "flash_attention"]


def __getattr__(name):
    # flash_attention pulls jax.experimental.pallas (+ its TPU backend),
    # a measurably slow import — load it only when asked for
    if name == "flash_attention":
        from mmlspark_tpu.ops.flash_attention import flash_attention
        return flash_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
