"""Pallas flash attention: the fused single-device attention kernel.

The dense `attention` (ops/attention.py) materializes the (S, S) score
matrix in HBM — fine until S grows; flash attention streams K/V blocks
through VMEM with online-softmax accumulators so peak memory is
O(block_q x block_k) per core and the QK^T / PV matmuls run back-to-back on
the MXU without a round trip to HBM.  This is the single-chip hot op of the
long-context stack (across chips, `ring_attention` shards S over the mesh
and uses the same online-softmax algebra; the reference has no sequence
dimension at all — SURVEY §5).

Semantics match `attention(q, k, v, causal, scale)` exactly: inputs
(B, S, H, D), float32 softmax statistics, scale defaulting to D^-0.5.
Backward is a custom VJP over two blocked pallas kernels (dQ, and dK/dV)
that recompute the score blocks against the forward's saved log-sum-exp —
the standard flash backward: no O(S^2) matrix is ever materialized, P is
rebuilt one (block_q, block_k) tile at a time as exp(S - LSE), and
dS = P * (dP - delta) with delta = rowsum(dO * O) precomputed in XLA.
Shapes that don't tile the blocks fall back to the dense VJP.

On CPU (tests, virtual meshes) the kernel runs in interpreter mode
automatically; shapes that don't tile (S not divisible by the block sizes)
fall back to the dense path rather than padding silently.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mmlspark_tpu.ops.attention import NEG_INF, attention


def _flash_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                  m_ref, l_ref, *, scale: float, causal: bool, block_q: int,
                  block_k: int, lse_ref=None):
    """One (batch*head, q-block, k-block) grid step.

    The grid's innermost dimension walks the K/V blocks; the online-softmax
    state (acc, running max m, normalizer l) lives in VMEM scratch that
    persists across those steps (TPU grids execute minor-to-major on one
    core), so VMEM holds only one K/V block at a time — sequence length is
    bounded by HBM, not by the 16 MB VMEM (a whole-K/V-in-VMEM layout tops
    out around S=16k at D=64)."""
    qi = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = qoff_ref[0]   # global position offsets (ring attention calls
    k_off = koff_ref[0]   # with rotating K/V shard origins; 0 standalone)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    if causal:
        # K/V blocks entirely above the diagonal contribute nothing
        live = (k_off + j * block_k) <= (q_off + (qi + 1) * block_q - 1)
    else:
        live = j >= 0

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale          # (block_q, d)
        kb = k_ref[0].astype(jnp.float32)                 # (block_k, d)
        vb = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = q_off + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_off + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m = m_ref[:][:, :1]                               # (block_q, 1)
        l = l_ref[:][:, :1]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # fully-masked-row guards (same algebra as ring_attention's fold)
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.where(s == NEG_INF, 0.0, jnp.exp(s - safe_m))
        corr = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # lane-broadcast the (block_q, 1) stats into the (block_q, 128)
        # scratch tiles (sub-lane scratch writes aren't supported)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _():
        m = m_ref[:][:, :1]
        l = l_ref[:][:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            # log-sum-exp of the scaled scores: the residual that lets a
            # caller (ring attention) merge normalized partial outputs.
            # The block is (block_q, 1) — a rank-3 (bh, sq, 1) output
            # layout, because mosaic requires the last two block dims to
            # divide (8, 128) or equal the array dims, which a rank-2
            # (1, block_q) lse block cannot satisfy for b*h > 1
            lse_ref[0] = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool, with_lse: bool = False,
                   q_offset=0, k_offset=0):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, head)
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff = jnp.asarray(k_offset, jnp.int32).reshape(1)

    # under shard_map (ring attention) outputs must declare which mesh axes
    # they vary over; inherit the query's varying-manual-axes type
    vma = _vma_of(q)
    sds = (functools.partial(jax.ShapeDtypeStruct, vma=vma)
           if vma else jax.ShapeDtypeStruct)
    out_shapes = [sds((b * h, sq, d), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, d), lambda bh, qi, j: (bh, qi, 0))]
    if with_lse:
        # rank-3 (bh, sq, 1) lse: blocks (1, block_q, 1) tile legally on
        # mosaic (block_q % 8 == 0); squeezed after the call
        out_shapes.append(sds((b * h, sq, 1), jnp.float32))
        out_specs.append(pl.BlockSpec((1, block_q, 1),
                                      lambda bh, qi, j: (bh, qi, 0)))

    def kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, *rest):
        if with_lse:
            lse_ref, acc_ref, m_ref, l_ref = rest
        else:
            (acc_ref, m_ref, l_ref), lse_ref = rest, None
        _flash_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref,
                      acc_ref, m_ref, l_ref, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, lse_ref=lse_ref)

    results = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, j: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, j: (bh, j, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shapes if with_lse else out_shapes[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max (lane-bcast)
            pltpu.VMEM((block_q, 128), jnp.float32),  # normalizer (lane-bcast)
        ],
        interpret=interpret,
    )(qoff, koff, q3, k3, v3)
    if with_lse:
        out, lse = results
        return (out.reshape(b, h, sq, d).transpose(0, 2, 1, 3),
                lse.reshape(b, h, sq).transpose(0, 2, 1))  # drops the 1-lane
    return results.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _bwd_p_block(q_ref, k_ref, lse_ref, *, scale, causal, block_q, block_k,
                 qi, j, q_off, k_off):
    """Recompute one probability tile P = exp(S - LSE) from saved stats.

    Shared by both backward kernels.  Rows whose LSE is NEG_INF (fully
    masked) and masked score entries produce exact zeros, so padding /
    above-diagonal tiles contribute nothing."""
    q = q_ref[0].astype(jnp.float32) * scale              # (block_q, d)
    kb = k_ref[0].astype(jnp.float32)                     # (block_k, d)
    s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        rows = q_off + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = k_off + j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    lse = lse_ref[0]                                      # (block_q, 1)
    p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))
    return jnp.where((s == NEG_INF) | (lse == NEG_INF), 0.0, p)


def _dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_acc, *, scale, causal, block_q,
               block_k):
    """dQ grid step: (batch*head, q-block, k-block), k innermost.

    dS = P * (dP - delta) with dP = dO V^T; dQ_i = scale * sum_j dS @ K_j
    accumulated in VMEM scratch across the innermost k walk."""
    qi, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_off, k_off = qoff_ref[0], koff_ref[0]

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    if causal:
        live = (k_off + j * block_k) <= (q_off + (qi + 1) * block_q - 1)
    else:
        live = j >= 0

    @pl.when(live)
    def _():
        p = _bwd_p_block(q_ref, k_ref, lse_ref, scale=scale, causal=causal,
                         block_q=block_q, block_k=block_k, qi=qi, j=j,
                         q_off=q_off, k_off=k_off)
        do = do_ref[0].astype(jnp.float32)                # (block_q, d)
        vb = v_ref[0].astype(jnp.float32)                 # (block_k, d)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])                      # delta: (block_q, 1)
        dq_acc[:] += scale * jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                block_q, block_k):
    """dK/dV grid step: (batch*head, k-block, q-block), q innermost.

    dV_j = sum_i P^T dO_i; dK_j = scale * sum_i dS^T Q_i — one pass over
    the q blocks per k block, accumulators in VMEM scratch."""
    j, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    q_off, k_off = qoff_ref[0], koff_ref[0]

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    if causal:
        live = (k_off + j * block_k) <= (q_off + (qi + 1) * block_q - 1)
    else:
        live = qi >= 0

    @pl.when(live)
    def _():
        p = _bwd_p_block(q_ref, k_ref, lse_ref, scale=scale, causal=causal,
                         block_q=block_q, block_k=block_k, qi=qi, j=j,
                         q_off=q_off, k_off=k_off)
        do = do_ref[0].astype(jnp.float32)                # (block_q, d)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])                      # delta: (block_q, 1)
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, do, lse, delta, causal, scale, block_q,
                    block_k, interpret, q_offset=0, k_offset=0):
    """Blocked backward from saved statistics: (dq, dk, dv).

    `lse`/`delta` are (B, Sq, H) float32 — the forward's log-sum-exp and
    rowsum(dO * O).  Two pallas launches (dQ walks k blocks; dK/dV walks q
    blocks) so each output has exactly one accumulating writer — no
    cross-grid-row races, no atomics (TPU grids are sequential per core,
    parallel across cores only over the batch*head dimension)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    do3 = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # stats ride as rank-3 (bh, sq, 1): see the forward's lse layout note
    lse2 = lse.transpose(0, 2, 1).reshape(b * h, sq, 1)
    delta2 = delta.transpose(0, 2, 1).reshape(b * h, sq, 1)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff = jnp.asarray(k_offset, jnp.int32).reshape(1)

    vma = _vma_of(q)
    sds = (functools.partial(jax.ShapeDtypeStruct, vma=vma)
           if vma else jax.ShapeDtypeStruct)

    def in_specs(q_map, k_map):
        """q_map/k_map: grid-indices -> (bh, block-row) for q-side and
        k-side operands respectively (the two kernels transpose the grid)."""
        return [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda *g: (*q_map(*g), 0)),  # q
            pl.BlockSpec((1, block_k, d), lambda *g: (*k_map(*g), 0)),  # k
            pl.BlockSpec((1, block_k, d), lambda *g: (*k_map(*g), 0)),  # v
            pl.BlockSpec((1, block_q, d), lambda *g: (*q_map(*g), 0)),  # do
            pl.BlockSpec((1, block_q, 1), lambda *g: (*q_map(*g), 0)),  # lse
            pl.BlockSpec((1, block_q, 1), lambda *g: (*q_map(*g), 0)),  # delta
        ]

    dq3 = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=in_specs(q_map=lambda bh, qi, j: (bh, qi),
                          k_map=lambda bh, qi, j: (bh, j)),
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, j: (bh, qi, 0)),
        out_shape=sds((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qoff, koff, q3, k3, v3, do3, lse2, delta2)

    dk3, dv3 = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=in_specs(q_map=lambda bh, j, qi: (bh, qi),
                          k_map=lambda bh, j, qi: (bh, j)),
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, j, qi: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j, qi: (bh, j, 0)),
        ],
        out_shape=[sds((b * h, sk, d), k.dtype),
                   sds((b * h, sk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qoff, koff, q3, k3, v3, do3, lse2, delta2)

    unshape_q = lambda a: a.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    unshape_k = lambda a: a.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    return unshape_q(dq3), unshape_k(dk3), unshape_k(dv3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    # the lse residual rides as rank-3 (bh, sq, 1) inside the kernels and
    # the backward's dK/dV output blocks are (1, block_k, d): real TPU
    # needs sublane-multiple block_q AND block_k there (interpret mode
    # does not); without them the backward will be the dense VJP, so
    # don't pay for lse in the forward
    if interpret or (block_q % 8 == 0 and block_k % 8 == 0):
        out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                                  interpret, with_lse=True)
        # named residuals: under jax.checkpoint with the 'save_attention'
        # policy (models/definitions.py) these are STORED, so the remat
        # backward reuses them instead of re-running the forward kernel
        from jax.ad_checkpoint import checkpoint_name
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return out, (q, k, v, out, lse)
    out = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, None, None)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if lse is not None:
        delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
        return _flash_backward(q, k, v, g, lse, delta, causal, scale,
                               block_q, block_k, interpret)
    # non-sublane-multiple block_q on real TPU: the dense VJP of the
    # same function (dense and flash forwards agree to float32 rounding)
    _, vjp = jax.vjp(lambda q_, k_, v_: attention(q_, k_, v_, causal=causal,
                                                  scale=scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


_warned_fallbacks: set = set()


def _warn_dense_fallback(fn_name: str, sq: int, sk: int, block_q: int,
                         block_k: int, interpret: bool,
                         reason: str) -> None:
    """The dense fallback is O(Sq x Sk) memory — silent on a long-context
    shard it is exactly the blow-up the flash path exists to avoid, so it
    must be visible.  Deduped per (fn, reason) with the first-seen shape in
    the message — a long-running scoring service cycling through many
    distinct sequence lengths must neither re-warn per shape nor grow the
    dedup set unboundedly; real-compute paths only (the interpreter already
    implies a test/CPU context)."""
    key = (fn_name, reason)
    if interpret or key in _warned_fallbacks:
        return
    _warned_fallbacks.add(key)
    from mmlspark_tpu.observe import get_logger
    get_logger("ops.flash").warning(
        "%s (first seen at Sq=%d, Sk=%d, blocks %d x %d): %s — falling "
        "back to DENSE attention (O(Sq*Sk) memory); warned once per reason",
        fn_name, sq, sk, block_q, block_k, reason)


def _vma_of(x):
    """The array type's varying-manual-axes, or None.  jax.typeof landed
    in 0.5.x — older builds have no vma tracking, so None there."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    return getattr(typeof(x), "vma", None)


def _in_manual_region(x) -> bool:
    """True inside a shard_map manual region (the array type carries
    varying-manual-axes); the pallas interpreter cannot run there."""
    return bool(_vma_of(x))


def _auto_interpret() -> bool:
    # interpreter off only on real TPU compute (the `axon` tunneled
    # platform reports device_kind "TPU v5 ..." with its own backend
    # name, so match the device kind, not the backend string)
    kind = getattr(jax.devices()[0], "device_kind", "")
    return "tpu" not in kind.lower()


def _dense_with_lse(q, k, v, causal, scale, q_offset, k_offset):
    """Reference-shape fallback: dense attention that also returns the
    scaled-score log-sum-exp per query (f32), with global-position causal
    masking."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(s.shape[-2])
        k_pos = k_offset + jnp.arange(s.shape[-1])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    m = s.max(axis=-1)
    safe_m = jnp.where(m == NEG_INF, 0.0, m)
    p = jnp.where(s == NEG_INF, 0.0, jnp.exp(s - safe_m[..., None]))
    l = p.sum(axis=-1)
    lse = jnp.where(l == 0.0, NEG_INF, safe_m + jnp.log(jnp.maximum(l, 1e-30)))
    out = jnp.einsum("bhqk,bkhd->bqhd", p / jnp.maximum(l, 1e-30)[..., None],
                     v.astype(jnp.float32))
    return out.astype(q.dtype), lse.transpose(0, 2, 1)  # lse: (B, Sq, H)


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = False,
                             scale: Optional[float] = None,
                             q_offset=0, k_offset=0,
                             block_q: int = 1024, block_k: int = 1024,
                             interpret: Optional[bool] = None):
    """Flash attention that ALSO returns the log-sum-exp residual
    (B, Sq, H) — the merge key for combining normalized partial outputs
    across K/V shards (ring_flash_attention).  `q_offset`/`k_offset` shift
    global positions for causal masking when q / k are shards of a longer
    sequence.  Forward-only (no VJP): the scoring/inference path.

    On real TPU, block_q must be a sublane multiple (8) for the rank-3
    lse output; non-tiling shapes fall back to the dense computation."""
    d = q.shape[-1]
    scale_ = scale if scale is not None else d ** -0.5
    sq, sk = q.shape[1], k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if interpret is None:
        interpret = _auto_interpret()
    # inside shard_map (ring attention) the pallas INTERPRETER trips on
    # varying-manual-axes bookkeeping; the dense local op is equivalent
    # there (CPU test meshes) while real TPU compiles the kernel
    in_manual_region = _in_manual_region(q)
    if sq % block_q or sk % block_k:
        _warn_dense_fallback(
            "flash_attention_with_lse", sq, sk, block_q, block_k, interpret,
            "sequence lengths do not tile the blocks (pad the sequence or "
            "adjust block sizes)")
        return _dense_with_lse(q, k, v, causal, scale_, q_offset, k_offset)
    if not interpret and (block_q % 8 or block_k % 8):
        _warn_dense_fallback(
            "flash_attention_with_lse", sq, sk, block_q, block_k, interpret,
            "the lse output / (1, block_k, d) K-V blocks need "
            "sublane-multiple block_q and block_k (8) on TPU")
        return _dense_with_lse(q, k, v, causal, scale_, q_offset, k_offset)
    if interpret and in_manual_region:
        return _dense_with_lse(q, k, v, causal, scale_, q_offset, k_offset)
    return _flash_forward(q, k, v, causal, scale_, block_q, block_k,
                          interpret, with_lse=True,
                          q_offset=q_offset, k_offset=k_offset)


def _dense_block_grads(q, k, v, do, lse, delta, causal, scale,
                       q_offset, k_offset):
    """Dense equivalent of `flash_block_grads` (fallback path): the
    gradient CONTRIBUTION of one K/V block given the global softmax
    statistics — not the VJP of local attention, whose normalizer would be
    this block's alone."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(s.shape[-2])
        k_pos = k_offset + jnp.arange(s.shape[-1])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    lse_b = lse.transpose(0, 2, 1)[..., None]             # (B,H,Sq,1)
    p = jnp.exp(s - jnp.where(lse_b == NEG_INF, 0.0, lse_b))
    p = jnp.where((s == NEG_INF) | (lse_b == NEG_INF), 0.0, p)
    do32 = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do32, v.astype(jnp.float32))
    ds = p * (dp - delta.transpose(0, 2, 1)[..., None])
    dq = scale * jnp.einsum("bhqk,bkhd->bqhd", ds, k.astype(jnp.float32))
    dk = scale * jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def flash_block_grads(q, k, v, do, lse, delta, causal: bool, scale: float,
                      q_offset=0, k_offset=0, block_q: int = 1024,
                      block_k: int = 1024,
                      interpret: Optional[bool] = None):
    """(dq, dk, dv) contribution of ONE K/V shard against global statistics.

    The building block of the ring backward (ops/attention.py
    `ring_flash_attention`): `lse` and `delta` are the FULL-sequence
    log-sum-exp and rowsum(dO * O), both (B, Sq, H) float32, so
    P = exp(S - LSE) is the true global probability of this block's keys
    and the per-block contributions simply sum around the ring.  Offsets
    place the shards in global positions for causal masking.  Falls back
    to the dense per-block computation for non-tiling shapes or inside a
    shard_map region on the interpreter (CPU test meshes)."""
    sq, sk = q.shape[1], k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if interpret is None:
        interpret = _auto_interpret()
    if sq % block_q or sk % block_k:
        _warn_dense_fallback(
            "flash_block_grads", sq, sk, block_q, block_k, interpret,
            "sequence lengths do not tile the blocks (pad the sequence or "
            "adjust block sizes)")
        return _dense_block_grads(q, k, v, do, lse, delta, causal, scale,
                                  q_offset, k_offset)
    if not interpret and (block_q % 8 or block_k % 8):
        _warn_dense_fallback(
            "flash_block_grads", sq, sk, block_q, block_k, interpret,
            "the lse/delta operands and (1, block_k, d) dK/dV blocks need "
            "sublane-multiple block_q and block_k (8) on TPU")
        return _dense_block_grads(q, k, v, do, lse, delta, causal, scale,
                                  q_offset, k_offset)
    if interpret and _in_manual_region(q):
        return _dense_block_grads(q, k, v, do, lse, delta, causal, scale,
                                  q_offset, k_offset)
    return _flash_backward(q, k, v, do, lse, delta, causal, scale,
                           block_q, block_k, interpret,
                           q_offset=q_offset, k_offset=k_offset)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused blocked attention; drop-in for `attention(q, k, v, causal)`.

    q: (B, Sq, H, D), k/v: (B, Sk, H, D) -> (B, Sq, H, D).  Block sizes
    clamp to the sequence lengths; shapes that still don't tile evenly
    fall back to the dense path (correctness first — padding KV silently
    would corrupt the softmax normalizer).  Defaults measured best on v5e
    at D=64 (8k ctx: 2.1x over 512-blocks; much larger k blocks overflow
    the double-buffered VMEM pipeline).
    """
    d = q.shape[-1]
    scale_ = scale if scale is not None else d ** -0.5
    sq, sk = q.shape[1], k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if interpret is None:
        interpret = _auto_interpret()
    if sq % block_q or sk % block_k:
        _warn_dense_fallback(
            "flash_attention", sq, sk, block_q, block_k, interpret,
            "sequence lengths do not tile the blocks (pad the sequence or "
            "adjust block sizes)")
        return attention(q, k, v, causal=causal, scale=scale_)
    # same guard as flash_attention_with_lse: inside shard_map the pallas
    # INTERPRETER (CPU test meshes) trips on varying-manual-axes
    # bookkeeping; the dense local op is equivalent there
    if interpret and _in_manual_region(q):
        return attention(q, k, v, causal=causal, scale=scale_)
    return _flash(q, k, v, causal, scale_, block_q, block_k, interpret)
