"""Typed metric contract (reference Metrics.scala:37-47).

The reference funnels every computed metric through `MetricData(data,
metricType, modelName)` — a metric-name -> column-of-doubles table tagged
with what kind of evaluation produced it — consumed by its logging layer
(ComputeModelStatistics.scala:486-521 logs full ROC tables through it).
`MetricData` here is the same contract as a frozen dataclass: evaluators and
the Trainer emit them, `log()` routes them through the logger factory, and
`to_table()` turns one back into a DataTable for pipeline consumption.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Mapping, Sequence

from mmlspark_tpu.observe.logging import get_logger

# --------------------------------------------------------------------------
# Framework counters: monotonically increasing process-wide tallies that
# subsystems (retry/breaker/chaos, checkpoint rotation) bump on events.
# Deliberately tiny — a dict under a lock — so the resilience hot paths can
# afford to increment on every attempt; `counters_metric_data()` folds the
# current tallies into the same MetricData contract everything else speaks.
# --------------------------------------------------------------------------

_counters: dict[str, float] = {}
_counters_lock = threading.Lock()


def inc_counter(name: str, value: float = 1.0) -> None:
    """Add `value` to the named process-wide counter (creates at 0)."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0.0) + value


def get_counter(name: str) -> float:
    """Current value of one counter (0.0 if never incremented)."""
    with _counters_lock:
        return _counters.get(name, 0.0)


def counters_snapshot() -> dict[str, float]:
    """A point-in-time copy of every counter."""
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    """Zero all counters (test isolation)."""
    with _counters_lock:
        _counters.clear()


def counters_metric_data() -> "MetricData":
    """The counter table as a MetricData row (metric_type='counters')."""
    snap = counters_snapshot()
    return MetricData.create(snap, "counters", "framework")


@dataclasses.dataclass(frozen=True)
class MetricData:
    """A metric table: name -> equal-length columns of floats, tagged with
    the metric type (e.g. "classification", "regression", "training") and
    the model that produced it."""

    data: dict[str, list[float]]
    metric_type: str
    model_name: str

    def __post_init__(self):
        lengths = {len(v) for v in self.data.values()}
        if len(lengths) > 1:
            raise ValueError(
                f"all metric columns must have the same length; got "
                f"{ {k: len(v) for k, v in self.data.items()} }")

    @classmethod
    def create(cls, data: Mapping[str, float], metric_type: str,
               model_name: str) -> "MetricData":
        """One scalar per metric (Metrics.scala:40-42)."""
        return cls({k: [float(v)] for k, v in data.items()},
                   metric_type, model_name)

    @classmethod
    def create_table(cls, data: Mapping[str, Sequence[float]],
                     metric_type: str, model_name: str) -> "MetricData":
        """A column of values per metric (Metrics.scala:43-45) — e.g. a ROC
        table, or per-epoch training losses."""
        return cls({k: [float(x) for x in v] for k, v in data.items()},
                   metric_type, model_name)

    @property
    def num_rows(self) -> int:
        return len(next(iter(self.data.values()))) if self.data else 0

    def scalars(self) -> dict[str, float]:
        """The single-row view; raises if any column has multiple rows."""
        if self.num_rows > 1:
            raise ValueError(f"metric table has {self.num_rows} rows; "
                             "use .data for tables")
        return {k: v[0] for k, v in self.data.items()}

    def to_table(self):
        import numpy as np

        from mmlspark_tpu.core.table import DataTable
        return DataTable({k: np.asarray(v, dtype=np.float64)
                          for k, v in self.data.items()})

    def log(self, suffix: str = "metrics", level: str = "info") -> None:
        """Route through the namespaced logger (the reference's
        logMetricData path)."""
        logger = get_logger(suffix)
        getattr(logger, level)("%s", self)

    def __str__(self):
        if self.num_rows == 1:
            body = ", ".join(f"{k}={v[0]:.6g}" for k, v in self.data.items())
        else:
            body = ", ".join(f"{k}[{len(v)}]" for k, v in self.data.items())
        return f"[{self.metric_type}] {self.model_name}: {body}"
