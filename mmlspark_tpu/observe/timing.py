"""Per-stage wall timers for fit/transform (the observability the reference
got from Spark's UI stage view, and from TestBase's logTime alerting,
TestBase.scala:138-153).

Opt-in and zero-cost when inactive: every PipelineStage subclass's `fit` /
`transform` is wrapped at class-creation time (core/pipeline.py hooks
`instrument_stage_method` from __init_subclass__); the wrapper checks one
context variable and takes the fast path out when no collector is active.

    with stage_timing() as times:
        model = pipeline.fit(table)
        scored = model.transform(table)
    print(times.table())

Nested stages (Pipeline.fit driving per-stage fits) record with their call
depth, so the table reads as a tree.  Wall time on an async backend counts
dispatch + any sync the stage itself performs — stages that return host
arrays (all of ours) have fully-accounted walls.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import time
from typing import Optional

_collector: contextvars.ContextVar[Optional["StageTimings"]] = \
    contextvars.ContextVar("mmlspark_tpu_stage_timings", default=None)


class StageTimings:
    """Collected (depth, stage, uid, method, seconds) records."""

    def __init__(self):
        self.records: list[dict] = []
        self._depth = 0

    def table(self) -> str:
        """The stage-time table, indented by call depth."""
        if not self.records:
            return "(no stages timed)"
        name_w = max(2 * r["depth"] + len(r["stage"]) + 1 + len(r["method"])
                     for r in self.records)
        lines = [f"{'stage'.ljust(name_w)}  seconds"]
        for r in self.records:
            name = f"{'  ' * r['depth']}{r['stage']}.{r['method']}"
            lines.append(f"{name.ljust(name_w)}  {r['seconds']:8.3f}")
        return "\n".join(lines)

    def total(self, stage: Optional[str] = None) -> float:
        """Sum of top-level stage walls (nested calls excluded to avoid
        double counting), optionally for one stage class."""
        return sum(r["seconds"] for r in self.records
                   if r["depth"] == 0 and (stage is None or r["stage"] == stage))

    def __str__(self):
        return self.table()


@contextlib.contextmanager
def stage_timing():
    """Activate stage timing for the dynamic extent of the block."""
    timings = StageTimings()
    token = _collector.set(timings)
    try:
        yield timings
    finally:
        _collector.reset(token)


def instrument_stage_method(method_name: str, fn):
    """Wrap a fit/transform definition; called from PipelineStage's
    __init_subclass__ so every stage in and out of the framework is covered
    without per-stage code."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        timings = _collector.get()
        if timings is None:
            return fn(self, *args, **kwargs)
        # type(self), not the defining class: a subclass inheriting
        # transform must show under its own name in the timing tree
        record = {"depth": timings._depth, "stage": type(self).__name__,
                  "uid": getattr(self, "uid", "?"), "method": method_name,
                  "seconds": 0.0}
        timings.records.append(record)  # pre-insert: tree order, not finish order
        timings._depth += 1
        t0 = time.perf_counter()
        try:
            return fn(self, *args, **kwargs)
        finally:
            record["seconds"] = time.perf_counter() - t0
            timings._depth -= 1

    wrapper.__mmlspark_instrumented__ = True
    return wrapper
