"""Hierarchical structured run traces: the event model under run_telemetry.

`spans.py` answers "how much total thread-time went to each pipeline
PHASE"; this module answers the question that aggregate cannot: *what did
step 1234 of that preempted run actually do* — every step, batch, decode
segment, retry, and checkpoint as a structured record with identity
(span id / parent id), a monotonic timestamp, a duration, and typed
attributes, survivable past the process.

Three consumers drive the design:

  * the **ring** — a bounded in-memory deque of completed records, so a
    live debugger (or `RunTelemetry.summary()`) can inspect the recent
    past without unbounded growth;
  * the **JSONL sink** — when a `Tracer` is given a sink path, every
    completed record streams to disk as one JSON line the moment it
    closes, so a preempted/killed run leaves a readable `run.jsonl`
    prefix (the same torn-tail tolerance checkpoints already have);
  * the **Chrome trace / Perfetto exporter** — `chrome_trace()` renders
    the ring as `trace_event` JSON (`ph: "X"` complete spans, `ph: "i"`
    instants) so a run log opens in Perfetto next to a `jax.profiler`
    dump (observe/profiler.py) with the same timeline idiom.

Propagation follows the capture-by-closure rule spans.py established:
the ambient tracer and current-span id live in contextvars (nested
`trace_span` blocks parent automatically on ONE thread), but prefetcher
worker threads never inherit contextvars — hot loops capture
`active_tracer()` plus a parent span handle ONCE on the consumer thread
and pass both into staging closures, recording worker-side spans with
`tracer.span(name, parent=handle)` explicitly.

Zero-cost when inactive (the `active_timings()` pattern): `trace_span` /
`trace_event` read one contextvar and return immediately when no tracer
is active, so instrumented hot loops pay a single None-check per pass.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Iterator, Optional

from mmlspark_tpu import config

DEFAULT_RING = 4096  # completed records kept in memory (the JSONL sink,
# when configured, has already persisted everything that scrolls off)

TRACE = config.register(
    "MMLSPARK_TPU_TRACE", True,
    "distributed tracing: propagate a per-request TraceContext through "
    "router dispatch, the KV handoff, and the data-service worker frames "
    "(0 disables the context plumbing; span/event recording under "
    "run_telemetry is governed by MMLSPARK_TPU_TELEMETRY)", ptype=bool)
TRACE_SAMPLE = config.register(
    "MMLSPARK_TPU_TRACE_SAMPLE", 1.0,
    "distributed tracing: head-sampled fraction of requests that keep "
    "full per-stage span detail in assembled waterfalls; the bit is "
    "derived from the trace id, so every tier of a fleet derives the "
    "SAME decision with no coordination.  Requests outside the fraction "
    "are still tail-promoted when they finish slow/shed/errored/hedged",
    ptype=float)
TRACE_SLOW_S = config.register(
    "MMLSPARK_TPU_TRACE_SLOW_S", 1.0,
    "distributed tracing: tail-sampling latency threshold — a request "
    "outside the head-sampled fraction that completes slower than this "
    "(seconds) is promoted to full-detail anyway (slow requests are "
    "exactly the ones worth a waterfall)", ptype=float)

_tracer_var: contextvars.ContextVar[Optional["Tracer"]] = \
    contextvars.ContextVar("mmlspark_tpu_tracer", default=None)
_span_var: contextvars.ContextVar[Optional[int]] = \
    contextvars.ContextVar("mmlspark_tpu_current_span", default=None)


class Span:
    """One open span: identity + start time + mutable attrs.

    Closed (and recorded) by `finish()` / context-manager exit; attrs may
    be added any time before that (`sp.attrs["loss"] = ...`), so a step
    span can carry results only known after the step ran.
    """

    __slots__ = ("name", "span_id", "parent_id", "cat", "attrs",
                 "t0", "_tracer", "_tid", "_done")

    def __init__(self, tracer: "Tracer", name: str, span_id: int,
                 parent_id: Optional[int], cat: str, attrs: dict):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.cat = cat
        self.attrs = attrs
        self._tracer = tracer
        self._tid = tracer._thread_id()
        self.t0 = tracer.now()
        self._done = False

    def elapsed(self) -> float:
        """Seconds since this span opened (for rate attrs computed before
        the span closes)."""
        return self._tracer.now() - self.t0

    def finish(self) -> dict:
        """Close the span and record it; idempotent."""
        if self._done:
            return {}
        self._done = True
        rec = {"type": "span", "name": self.name, "id": self.span_id,
               "parent": self.parent_id, "cat": self.cat,
               "ts": round(self.t0, 6),
               "dur": round(self._tracer.now() - self.t0, 6),
               "thread": self._tid, "attrs": self.attrs}
        self._tracer._record(rec)
        return rec

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class Tracer:
    """One run's span/event recorder: bounded ring + optional JSONL sink.

    Thread-safe: spans open/close and events fire from the consumer
    thread and the prefetcher's staging workers alike.  Timestamps are
    monotonic seconds relative to the tracer's epoch; `wall0` pins that
    epoch to wall-clock time for cross-referencing with external logs.
    """

    def __init__(self, ring: int = DEFAULT_RING,
                 sink_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring)
        self._ids = itertools.count(1)
        self._threads: dict[int, int] = {}   # ident -> small stable tid
        self._t0 = time.perf_counter()
        self.wall0 = time.time()
        self._sink = open(sink_path, "w") if sink_path else None
        self.dropped = 0  # records that scrolled off the ring

    # -- time / identity -------------------------------------------------
    def now(self) -> float:
        return time.perf_counter() - self._t0

    def _thread_id(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._threads.get(ident)
            if tid is None:
                tid = self._threads[ident] = len(self._threads)
            return tid

    # -- recording -------------------------------------------------------
    def _record(self, rec: dict) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)
            if self._sink is not None:
                # default=str: an exotic attr value (numpy scalar, Path)
                # degrades to its repr instead of killing the hot loop
                self._sink.write(json.dumps(rec, default=str) + "\n")

    def span(self, name: str, *, parent: Optional[int] = None,
             cat: str = "span", **attrs) -> Span:
        """Open a span (context manager / `finish()`); `parent` is an
        explicit span id — the handle worker threads are passed, since
        they never see the consumer's contextvars."""
        return Span(self, name, next(self._ids), parent, cat, attrs)

    def event(self, name: str, *, parent: Optional[int] = None,
              cat: str = "event", **attrs) -> dict:
        """Record an instantaneous event (duration-free marker)."""
        rec = {"type": "event", "name": name, "id": next(self._ids),
               "parent": parent, "cat": cat, "ts": round(self.now(), 6),
               "thread": self._thread_id(), "attrs": attrs}
        self._record(rec)
        return rec

    def records(self) -> list[dict]:
        """A snapshot copy of the ring (completed records, oldest first)."""
        with self._lock:
            return list(self._ring)

    def flush(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.flush()

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None

    # -- aggregation / export --------------------------------------------
    def span_aggregates(self) -> dict[str, dict]:
        """Per-span-name {count, total_s, max_s} over the ring — the
        rollup run_summary.json and the Prometheus exposition share."""
        return aggregate_spans(self.records())

    def chrome_trace(self) -> dict:
        """The ring as Chrome-trace/Perfetto `trace_event` JSON."""
        return chrome_trace(self.records(), wall0=self.wall0)

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f, default=str)
        return path


def aggregate_spans(records: list[dict]) -> dict[str, dict]:
    """Per-name span rollup for a record list (see Tracer.span_aggregates)."""
    agg: dict[str, dict] = {}
    for rec in records:
        if rec.get("type") != "span":
            continue
        a = agg.setdefault(rec["name"], {"count": 0, "total_s": 0.0,
                                         "max_s": 0.0})
        a["count"] += 1
        a["total_s"] = round(a["total_s"] + rec["dur"], 6)
        a["max_s"] = round(max(a["max_s"], rec["dur"]), 6)
    return agg


def chrome_trace(records: list[dict], wall0: float = 0.0) -> dict:
    """Render span/event records as Chrome-trace (`trace_event`) JSON:
    `ph: "X"` complete events for spans, `ph: "i"` instants for events —
    the format Perfetto (and chrome://tracing) loads directly."""
    events = []
    for rec in records:
        kind = rec.get("type")
        if kind == "gauge":
            # gauges render as Chrome counter tracks (ph "C")
            events.append({"name": rec["name"], "ph": "C", "pid": 0,
                           "ts": round(rec["ts"] * 1e6, 3),
                           "args": {"value": rec["value"]}})
            continue
        if kind not in ("span", "event"):
            continue  # run_start / counters / stage_timings bookkeeping
        base = {"name": rec["name"], "pid": 0, "tid": rec.get("thread", 0),
                "cat": rec.get("cat", "span"),
                "ts": round(rec["ts"] * 1e6, 3),
                "args": {**rec.get("attrs", {}), "id": rec.get("id"),
                         "parent": rec.get("parent")}}
        if kind == "span":
            events.append({**base, "ph": "X",
                           "dur": round(rec["dur"] * 1e6, 3)})
        else:
            events.append({**base, "ph": "i", "s": "t"})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"wall_epoch_s": wall0,
                          "producer": "mmlspark_tpu.observe.trace"}}


# -- ambient propagation (consumer-thread convenience layer) ---------------

def active_tracer() -> Optional[Tracer]:
    """The ambient tracer, or None — hot loops read this ONCE per pass and
    pass the handle (plus a parent span id) into worker closures."""
    return _tracer_var.get()


def current_span_id() -> Optional[int]:
    """The ambient current span id (the parent handle to capture for
    worker-thread spans)."""
    return _span_var.get()


@contextlib.contextmanager
def tracing(tracer: Tracer) -> Iterator[Tracer]:
    """Activate `tracer` as the ambient tracer for the block (run_telemetry
    uses this; tests can too)."""
    token = _tracer_var.set(tracer)
    try:
        yield tracer
    finally:
        _tracer_var.reset(token)


@contextlib.contextmanager
def trace_span(name: str, cat: str = "span", **attrs) -> Iterator[Optional[Span]]:
    """Ambient span: parents under the enclosing trace_span on this
    thread, yields the open Span (or None, near-free, when no tracer is
    active — the hot-loop fast path)."""
    tracer = _tracer_var.get()
    if tracer is None:
        yield None
        return
    sp = tracer.span(name, parent=_span_var.get(), cat=cat, **attrs)
    token = _span_var.set(sp.span_id)
    try:
        with sp:
            yield sp
    finally:
        _span_var.reset(token)


def trace_event(name: str, cat: str = "event", **attrs) -> Optional[dict]:
    """Ambient instantaneous event; None (no record) when inactive."""
    tracer = _tracer_var.get()
    if tracer is None:
        return None
    return tracer.event(name, parent=_span_var.get(), cat=cat, **attrs)


@contextlib.contextmanager
def span_scope(span_id: Optional[int]) -> Iterator[None]:
    """Re-parent ambient spans under an explicit span id for the block —
    how a consumer loop nests its per-item spans under a phase span it
    opened manually with `tracer.span(...)`."""
    token = _span_var.set(span_id)
    try:
        yield
    finally:
        _span_var.reset(token)


def span_on_tracer(tracer: Optional[Tracer], name: str,
                   parent: Optional[int] = None, cat: str = "span",
                   **attrs) -> Any:
    """Span against a captured tracer handle; no-op context for None —
    the worker-thread counterpart of spans.span_on."""
    if tracer is None:
        return contextlib.nullcontext()
    return tracer.span(name, parent=parent, cat=cat, **attrs)


# -- distributed trace context (fleet-wide request tracing) -----------------
#
# A request that crosses a socket seam (data-service worker frames, the
# KV handoff, the HTTP front door) loses its span parentage: span ids are
# per-tracer integers with no cross-process meaning.  TraceContext is the
# Dapper-style identity that survives the wire — a 16-byte trace id (the
# request, everywhere), the sender-side parent span id (stitching hint),
# and the sampling bit — carried as one small JSON field on the existing
# control frames and re-attached on the far side.  observe/assemble.py
# joins shard records back into per-request waterfalls on the trace id.


def trace_enabled() -> bool:
    """The MMLSPARK_TPU_TRACE master switch for context propagation."""
    return bool(TRACE.current())


def new_trace_id() -> str:
    """Mint one 16-byte trace id as 32 lowercase hex chars.

    THE ONE SANCTIONED ID MINT: scripts/lint.py forbids uuid/secrets/
    os.urandom id generation everywhere else under mmlspark_tpu/, so
    cross-process stitching can rely on exactly this format."""
    return os.urandom(16).hex()


def head_sampled(trace_id: str, fraction: float) -> bool:
    """The head-sampling decision, derived FROM the trace id (first 4
    bytes as a uniform in [0, 1)): every tier of a fleet — router,
    prefill, decode, data-service workers — computes the same bit from
    the id alone, so the decision is consistent with no coordination
    and pinned across failover by construction."""
    if fraction >= 1.0:
        return True
    if fraction <= 0.0:
        return False
    return int(trace_id[:8], 16) / float(1 << 32) < fraction


class TraceContext:
    """One request's cross-process trace identity (module comment above).

    `sampled` is the HEAD decision and is immutable for the request's
    lifetime (the satellite consistency pin); tail promotion at
    completion is a separate `trace.tail_sample` event, never a flipped
    bit mid-flight.  `attempt` counts dispatch attempts (1-based) so a
    failover re-uses the trace id with a new attempt span."""

    __slots__ = ("trace_id", "parent_span", "sampled", "attempt")

    def __init__(self, trace_id: str, parent_span: Optional[int] = None,
                 sampled: bool = True, attempt: int = 1):
        self.trace_id = str(trace_id)
        self.parent_span = parent_span
        self.sampled = bool(sampled)
        self.attempt = int(attempt)

    def child(self, parent_span: Optional[int] = None,
              attempt: Optional[int] = None) -> "TraceContext":
        """Same trace id and sampling bit, new stitching point."""
        return TraceContext(
            self.trace_id,
            self.parent_span if parent_span is None else parent_span,
            self.sampled,
            self.attempt if attempt is None else attempt)

    def to_wire(self) -> dict:
        """The JSON control field that rides hello/graph/split frames and
        the kv_begin header."""
        return {"id": self.trace_id, "parent": self.parent_span,
                "sampled": self.sampled, "attempt": self.attempt}

    @classmethod
    def from_wire(cls, obj) -> Optional["TraceContext"]:
        """Parse the wire field; anything malformed degrades to None
        (an untraced request) rather than failing the frame."""
        if not isinstance(obj, dict):
            return None
        tid = obj.get("id")
        if not isinstance(tid, str) or not tid:
            return None
        parent = obj.get("parent")
        if not isinstance(parent, int):
            parent = None
        try:
            attempt = max(1, int(obj.get("attempt", 1)))
        except (TypeError, ValueError):
            attempt = 1
        return cls(tid, parent, bool(obj.get("sampled", True)), attempt)

    def attrs(self) -> dict:
        """The standard span/event attribute triple every traced record
        carries (assemble joins on `trace`)."""
        return {"trace": self.trace_id, "sampled": self.sampled,
                "attempt": self.attempt}

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id[:8]}…, "
                f"attempt={self.attempt}, sampled={self.sampled})")


def mint_context() -> Optional[TraceContext]:
    """Mint a fresh root context (router admission, bare-engine submit,
    data-service session start), or None when tracing is off — callers
    thread the None through and every downstream site stays untraced."""
    if not trace_enabled():
        return None
    tid = new_trace_id()
    return TraceContext(
        tid, sampled=head_sampled(tid, float(TRACE_SAMPLE.current())))


def tail_promote(ctx: Optional[TraceContext], *, status: str,
                 latency_s: Optional[float], hedged: bool = False,
                 retries: int = 0) -> Optional[str]:
    """The tail-sampling decision at request completion: a head-unsampled
    request that finished slow, shed, errored, timed out, hedged, or
    retried is worth full detail after all.  Returns the promotion
    reason (assemble keeps full waterfalls for promoted traces) or None;
    head-sampled requests need no promotion."""
    if ctx is None or ctx.sampled:
        return None
    if status not in ("ok",):
        return status
    if hedged:
        return "hedged"
    if retries > 0:
        return "retried"
    if latency_s is not None and latency_s > float(TRACE_SLOW_S.current()):
        return "slow"
    return None
