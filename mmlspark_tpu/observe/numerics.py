"""Numerics health: jitted tree probes + rolling loss-spike detection.

A NaN'd loss wastes everything downstream of it — the steps that keep
running, the checkpoint rotation that happily promotes the poisoned
state to LATEST, the bench run whose numbers are garbage.  The framework
measures everything else about a training run (PR 5); this module makes
it measure the run's *health*:

  * `tree_health` — a flat dict of scalar diagnostics over the step's
    trees, built to run INSIDE the jitted train step: non-finite counts
    (params / grads / the logits activation), global and per-layer-group
    grad/param norms, and per-group update-to-weight ratios (the
    learning-rate sanity signal).  Cadence control lives in the step
    itself (`lax.cond` on a traced `probe` flag — `train/trainer.py`),
    so off-cadence steps pay one predicate, not the reductions.
  * `LossSpikeDetector` — a rolling-median/MAD detector over the
    per-step loss: `nonfinite` immediately, `spike` when a loss jumps
    past the noise envelope, `divergence` when spikes sustain.  Verdicts
    emit resilience-style telemetry events, so they land in the same
    run-report timeline as retries and preemptions.
  * `NonFiniteError` — raised by the trainer (opt-in
    `TrainerConfig.halt_on_nonfinite`) when a probe sees non-finite
    state, BEFORE the step-boundary checkpoint runs: the last finite
    checkpoint stays LATEST instead of being rotated out by a poisoned
    one.

Chaos integration: `MMLSPARK_TPU_CHAOS_NAN_AT_STEP` (resilience/chaos.py)
poisons one step's loss mask with NaN, so detection-within-one-interval
and checkpoint preservation are testable, deterministically, on any
backend.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp

# layer groups: params are grouped by their top-level module name
# ("Dense_0", "blocks_2", ...) — coarse enough to stay a handful of
# scalars, fine enough to localize which block's gradients blew up


def _group_of(path) -> str:
    for p in path:
        key = getattr(p, "key", None)
        if key is not None:
            return str(key)
    return "params"


def _grouped_sq_sums(tree) -> dict:
    """{group: sum of squares} over a tree, one scalar per top-level
    module (runs under jit: static structure, scalar reductions)."""
    out: dict = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        g = _group_of(path)
        sq = jnp.sum(jnp.square(jnp.asarray(leaf, jnp.float32)))
        out[g] = out.get(g, 0.0) + sq
    return out


def _nonfinite_count(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(~jnp.isfinite(jnp.asarray(leaf, jnp.float32)))
               for leaf in leaves).astype(jnp.float32)


def tree_health(params, grads, updates, acts=None) -> dict:
    """The flat health dict (all float32 scalars), jit-safe.

    Keys: `nonfinite_params` / `nonfinite_grads` / `nonfinite_acts`
    (element counts), `grad_norm/<group>`, `param_norm/<group>`,
    `update_ratio/<group>` (||update|| / (||param|| + eps) — the
    update-to-weight ratio, the classic learning-rate health signal),
    plus `act_norm` over `acts` (the step's logits) when given.
    """
    eps = 1e-12
    health: dict = {
        "nonfinite_params": _nonfinite_count(params),
        "nonfinite_grads": _nonfinite_count(grads),
    }
    p_sq = _grouped_sq_sums(params)
    g_sq = _grouped_sq_sums(grads)
    u_sq = _grouped_sq_sums(updates)
    for g in p_sq:
        p_norm = jnp.sqrt(p_sq[g])
        health[f"param_norm/{g}"] = p_norm
        if g in g_sq:
            health[f"grad_norm/{g}"] = jnp.sqrt(g_sq[g])
        if g in u_sq:
            health[f"update_ratio/{g}"] = jnp.sqrt(u_sq[g]) / (p_norm + eps)
    if acts is not None:
        acts = jnp.asarray(acts, jnp.float32)
        health["act_norm"] = jnp.sqrt(jnp.sum(jnp.square(acts)))
        health["nonfinite_acts"] = jnp.sum(
            ~jnp.isfinite(acts)).astype(jnp.float32)
    return {k: jnp.asarray(v, jnp.float32) for k, v in health.items()}


def zeros_like_health(health: dict) -> dict:
    """The off-cadence lax.cond branch: same structure, zero cost."""
    return {k: jnp.zeros((), jnp.float32) for k in health}


class NonFiniteError(RuntimeError):
    """Training state went non-finite and halt_on_nonfinite is armed.

    Raised at the step boundary BEFORE any checkpoint write, so the
    newest checkpoint on disk is the last finite one.
    """

    def __init__(self, step: int, detail: str,
                 ckpt_dir: Optional[str] = None):
        self.step = step
        self.detail = detail
        self.ckpt_dir = ckpt_dir
        msg = (f"non-finite training state detected at step {step} "
               f"({detail})")
        if ckpt_dir:
            msg += (f"; halting before the poisoned state reaches a "
                    f"checkpoint — the newest valid checkpoint in "
                    f"{ckpt_dir} is the last finite state")
        super().__init__(msg)


class DivergenceError(RuntimeError):
    """The loss-spike detector returned a `divergence` verdict and
    halt_on_divergence is armed.

    Raised at the step boundary BEFORE any checkpoint write (same
    contract as NonFiniteError) so the newest checkpoint on disk is the
    last pre-divergence state — the restore point the recovery
    supervisor rolls back to.
    """

    def __init__(self, step: int, loss: float,
                 threshold: Optional[float] = None,
                 ckpt_dir: Optional[str] = None):
        self.step = step
        self.loss = loss
        self.threshold = threshold
        self.ckpt_dir = ckpt_dir
        msg = (f"loss divergence detected at step {step} (loss={loss:g}"
               + (f", spike threshold {threshold:g}" if threshold
                  is not None else "") + ")")
        if ckpt_dir:
            msg += (f"; halting before the diverged state reaches a "
                    f"checkpoint — restore from {ckpt_dir}")
        super().__init__(msg)


class LossSpikeDetector:
    """Rolling loss-health verdicts: ok | spike | divergence | nonfinite.

    Noise model: the rolling median and MAD of the last `window` FINITE
    losses define the envelope; a loss above
    `median + spike_sigmas * (1.4826 * MAD + eps)` is a `spike` (the
    MAD floor `min_rel * |median|` keeps an early flat history from
    flagging ordinary jitter), and `div_consecutive` consecutive spikes
    are a `divergence`.  Spiking observations do NOT enter the baseline
    — a diverging run cannot normalize its own spikes away.
    """

    def __init__(self, window: int = 25, spike_sigmas: float = 6.0,
                 min_rel: float = 0.1, div_consecutive: int = 3,
                 warmup: int = 5):
        self.window = window
        self.spike_sigmas = spike_sigmas
        self.min_rel = min_rel
        self.div_consecutive = div_consecutive
        self.warmup = warmup
        self._recent: deque = deque(maxlen=window)
        self._spike_run = 0

    def threshold(self) -> Optional[float]:
        """The current spike threshold, or None during warmup."""
        if len(self._recent) < self.warmup:
            return None
        xs = sorted(self._recent)
        n = len(xs)
        med = (xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2)
        mad = sorted(abs(x - med) for x in xs)[n // 2]
        sigma = max(1.4826 * mad, self.min_rel * abs(med), 1e-9)
        return med + self.spike_sigmas * sigma

    def update(self, loss: float) -> str:
        """Feed one per-step loss; returns the verdict for this step."""
        if not math.isfinite(loss):
            self._spike_run += 1
            return "nonfinite"
        thr = self.threshold()
        if thr is not None and loss > thr:
            self._spike_run += 1
            return ("divergence"
                    if self._spike_run >= self.div_consecutive else "spike")
        self._spike_run = 0
        self._recent.append(loss)
        return "ok"
