"""Persistent bench history: rolling baselines + regression verdicts.

    python -m mmlspark_tpu.observe.history ingest bench_out.json
    python -m mmlspark_tpu.observe.history check  bench_out.json --strict
    python -m mmlspark_tpu.observe.history show

bench.py emits one JSON line per metric and the driver keeps the latest
snapshot — nothing in the repo remembers the run before it, so a 20%
regression between invocations is invisible unless a human diffs files.
This module is the memory: an append-only JSONL store of every ingested
bench record, a noise-tolerant rolling baseline per (metric, field), and
a verdict per fresh record against its baseline.

  * **Store** — one JSON object per line, `{"kind": "bench", "run_id",
    "ingested_at", "record": {...}}`, append-only (the checkpoint-
    rotation posture: history is never rewritten).  Torn/partial lines —
    a killed ingest, a half-synced file — are skipped and counted,
    never raised on.
  * **Baselines** — per (metric, field): the median of the last
    `BASELINE_WINDOW` runs' values.  Tolerance is
    `max(rel_tol, mad_k * 1.4826 * MAD / |median|)` — the measured
    run-to-run noise widens the band, so a jittery metric does not page
    and a stable one stays tight.
  * **Verdicts** — `regression` / `improvement` when the fresh value
    leaves the band in the metric's bad/good direction (directions are
    inferred from field names: rates/MFU/accuracy up, milliseconds and
    overheads down), `ok` inside it, `new` with no baseline yet.

`check` computes verdicts WITHOUT appending (the CI mode `make
bench-smoke` wires against the committed baseline — report-only unless
`--strict`); `ingest` appends after judging, so the next run's baseline
includes this one.

This module is a CLI whose product is stdout text — whitelisted for raw
print() alongside observe/report.py (scripts/lint.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Iterable, Optional

from mmlspark_tpu import config
from mmlspark_tpu.observe.logging import get_logger

BENCH_HISTORY = config.register(
    "MMLSPARK_TPU_BENCH_HISTORY", default=None,
    doc="Default bench-history store path for "
        "`python -m mmlspark_tpu.observe.history` (--store overrides); "
        "unset: .bench_history.jsonl in the working directory.")

DEFAULT_STORE = ".bench_history.jsonl"
BASELINE_WINDOW = 8     # runs per rolling baseline
DEFAULT_REL_TOL = 0.10  # the floor of the tolerance band
MAD_K = 4.0             # noise widening: k * 1.4826 * MAD / |median|

# verdict directions by field-name shape; fields matching neither are
# tracked in the store but get no verdict (attribution fields like
# stage_*_s and link_* ride bench lines without being quality claims)
_HIGHER = ("value", "mfu", "device_mfu", "accuracy", "agreement",
           "hbm_bw_util")
_HIGHER_SUFFIX = ("_per_sec", "_per_chip", "_speedup", "_agreement",
                  "_accuracy", "_images_per_sec", "_tokens_per_sec")
_LOWER = ("telemetry_overhead", "trace_overhead", "train_wall_s")
_LOWER_SUFFIX = ("_step_ms", "_ms")


def direction(field: str) -> Optional[int]:
    """+1 higher-is-better, -1 lower-is-better, None untracked."""
    if field in _HIGHER or field.endswith(_HIGHER_SUFFIX):
        return 1
    if field in _LOWER or field.endswith(_LOWER_SUFFIX):
        return -1
    return None


def default_store() -> str:
    return BENCH_HISTORY.current() or DEFAULT_STORE


def load_history(path: str) -> list[dict]:
    """Parse the store; undecodable/foreign lines are skipped (logged),
    never raised on — a torn tail must not take down the check that
    exists to catch regressions."""
    entries: list[dict] = []
    skipped = 0
    if not os.path.exists(path):
        return entries
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(entry, dict) or \
                    not isinstance(entry.get("record"), dict) or \
                    "metric" not in entry["record"]:
                skipped += 1
                continue
            entries.append(entry)
    if skipped:
        get_logger("observe.history").warning(
            "%s: skipped %d torn/foreign line(s)", path, skipped)
    return entries


def load_bench_records(path: str) -> list[dict]:
    """Parse a bench.py output capture (JSON lines; non-JSON noise like
    backend warnings is skipped) into its metric records."""
    records = []
    stream = sys.stdin if path == "-" else open(path)
    try:
        for line in stream:
            line = line.strip()
            if not line or not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                records.append(rec)
    finally:
        if stream is not sys.stdin:
            stream.close()
    return records


def append_records(path: str, records: Iterable[dict],
                   meta: Optional[dict] = None) -> int:
    """Append one ingest (all `records` share a run_id); returns it."""
    history = load_history(path)
    run_id = 1 + max((e.get("run_id", 0) for e in history), default=0)
    with open(path, "a") as f:
        for rec in records:
            entry = {"kind": "bench", "run_id": run_id,
                     "ingested_at": round(time.time(), 3),
                     "record": rec}
            if meta:
                entry["meta"] = meta
            f.write(json.dumps(entry, sort_keys=True) + "\n")
    return run_id


def _series(history: list[dict], metric: str, field: str) -> list[float]:
    """The field's per-run series (newest last), one value per run_id."""
    by_run: dict = {}
    for e in history:
        rec = e["record"]
        if rec.get("metric") != metric:
            continue
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            by_run[e.get("run_id", 0)] = float(v)
    return [by_run[r] for r in sorted(by_run)]


def _median(xs: list[float]) -> float:
    ys = sorted(xs)
    n = len(ys)
    return ys[n // 2] if n % 2 else (ys[n // 2 - 1] + ys[n // 2]) / 2


def baseline(history: list[dict], metric: str, field: str,
             window: int = BASELINE_WINDOW) -> Optional[dict]:
    """{'median', 'mad', 'n'} over the last `window` runs, or None."""
    series = _series(history, metric, field)[-window:]
    if not series:
        return None
    med = _median(series)
    mad = _median([abs(x - med) for x in series])
    return {"median": med, "mad": mad, "n": len(series)}


def judge(history: list[dict], records: list[dict],
          rel_tol: float = DEFAULT_REL_TOL,
          mad_k: float = MAD_K) -> list[dict]:
    """Verdict rows for fresh bench `records` against the store."""
    rows = []
    for rec in records:
        metric = rec.get("metric")
        for field in sorted(rec):
            d = direction(field)
            v = rec.get(field)
            if d is None or not isinstance(v, (int, float)) \
                    or isinstance(v, bool):
                continue
            base = baseline(history, metric, field)
            if base is None or not base["median"]:
                rows.append({"metric": metric, "field": field,
                             "value": v, "baseline": None,
                             "ratio": None, "verdict": "new"})
                continue
            med = base["median"]
            tol = max(rel_tol, mad_k * 1.4826 * base["mad"] / abs(med))
            ratio = v / med
            delta = d * (ratio - 1.0)  # positive = better
            verdict = ("improvement" if delta > tol
                       else "regression" if delta < -tol else "ok")
            rows.append({"metric": metric, "field": field, "value": v,
                         "baseline": round(med, 6),
                         "ratio": round(ratio, 4), "tol": round(tol, 4),
                         "verdict": verdict})
    return rows


def render_verdicts(rows: list[dict]) -> str:
    lines = ["== bench history verdicts =="]
    flagged = [r for r in rows if r["verdict"] in ("regression",
                                                   "improvement")]
    for r in rows:
        mark = {"regression": "!!", "improvement": "++",
                "ok": "  ", "new": " ?"}[r["verdict"]]
        base = ("baseline n/a" if r["baseline"] is None else
                f"baseline {r['baseline']:g} ratio {r['ratio']:.3f} "
                f"tol {r['tol']:.3f}")
        lines.append(f"  {mark} {r['verdict']:<11} "
                     f"{r['metric']}.{r['field']}: {r['value']:g} "
                     f"({base})")
    lines.append(f"  {len(rows)} tracked field(s), "
                 f"{sum(1 for r in rows if r['verdict'] == 'regression')} "
                 f"regression(s), "
                 f"{sum(1 for r in rows if r['verdict'] == 'improvement')} "
                 f"improvement(s)")
    if not flagged:
        lines.append("  quiet: every tracked field within its baseline "
                     "band")
    return "\n".join(lines)


def render_store(history: list[dict]) -> str:
    lines = ["== bench history =="]
    if not history:
        return "== bench history ==\n  (empty store)"
    runs = sorted({e.get("run_id", 0) for e in history})
    metrics = sorted({e["record"].get("metric") for e in history})
    lines.append(f"  {len(history)} record(s) over {len(runs)} run(s)")
    for metric in metrics:
        lines.append(f"  {metric}:")
        fields = sorted({f for e in history
                         if e["record"].get("metric") == metric
                         for f in e["record"] if direction(f) is not None})
        for field in fields:
            base = baseline(history, metric, field)
            if base is None:
                continue
            arrow = {1: "^", -1: "v"}[direction(field)]
            lines.append(f"    {field:<36} median {base['median']:g} "
                         f"(mad {base['mad']:g}, n={base['n']}, "
                         f"better {arrow})")
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.observe.history",
        description="Append-only bench history: rolling baselines + "
                    "regression/improvement verdicts.")
    sub = parser.add_subparsers(dest="cmd", required=True)
    for name, doc in (("ingest", "judge against the store, then append"),
                      ("check", "judge only — the store is not touched")):
        p = sub.add_parser(name, help=doc)
        p.add_argument("bench", help="bench.py output capture "
                                     "(JSON lines; '-' = stdin)")
        p.add_argument("--store", default=None)
        p.add_argument("--rel-tol", type=float, default=DEFAULT_REL_TOL)
        p.add_argument("--strict", action="store_true",
                       help="exit 1 when any tracked field regresses")
        p.add_argument("--format", choices=("text", "json"),
                       default="text")
    p = sub.add_parser("show", help="render the store's baselines")
    p.add_argument("--store", default=None)
    args = parser.parse_args(argv)

    store = args.store or default_store()
    history = load_history(store)
    if args.cmd == "show":
        print(render_store(history))
        return 0

    records = load_bench_records(args.bench)
    if not records:
        print(f"no bench records in {args.bench}")
        return 1
    rows = judge(history, records, rel_tol=args.rel_tol)
    if args.cmd == "ingest":
        run_id = append_records(store, records)
        print(f"ingested {len(records)} record(s) into {store} "
              f"as run {run_id}")
    if args.format == "json":
        print(json.dumps(rows, indent=2, sort_keys=True))
    else:
        print(render_verdicts(rows))
    regressions = sum(1 for r in rows if r["verdict"] == "regression")
    return 1 if (args.strict and regressions) else 0


if __name__ == "__main__":
    sys.exit(main())
