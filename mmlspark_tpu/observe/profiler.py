"""Opt-in device profiling (what the reference never had — its JNI scoring
loop was unobservable; diagnosing round 2's throughput swing took manual
probing).

    with mmlspark_tpu.profile("/tmp/trace"):
        model.transform(table)

wraps jax.profiler.trace: the dump is a TensorBoard/Perfetto trace showing
host transfer vs MXU occupancy per step.  `annotate(name)` adds a named span
inside an active trace (jax.profiler.TraceAnnotation) around host-side code
so framework phases (batching, padding, fetch) are visible between device
ops.  The framework-side run record (`run_telemetry`'s trace.json,
observe/telemetry.py) uses the same Perfetto timeline idiom, so the two
dumps load side by side.
"""

from __future__ import annotations

import contextlib

import jax

from mmlspark_tpu.observe.logging import get_logger


@contextlib.contextmanager
def profile(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a device+host trace of the block into `log_dir`."""
    # Probe jax's trace() signature BEFORE entering the block: a TypeError
    # raised by user code inside the block must propagate untouched, never
    # be mistaken for an old-jax signature mismatch.
    kwargs: dict = {}
    try:
        import inspect
        if "profiler_options" in inspect.signature(
                jax.profiler.trace).parameters:
            options = jax.profiler.ProfileOptions()
            options.host_tracer_level = host_tracer_level
            kwargs["profiler_options"] = options
    except Exception as exc:
        # a REAL probe failure (import error, renamed API) must be visible
        # — a silently downgraded trace reads as "the chip was idle" and
        # sends the investigation the wrong way.  The trace itself still
        # runs: options are an enhancement, not a requirement.
        get_logger("observe").warning(
            "jax.profiler signature probe failed (%r); tracing without "
            "profiler_options (host_tracer_level=%d not applied)",
            exc, host_tracer_level)
    with jax.profiler.trace(log_dir, **kwargs):
        yield log_dir


def annotate(name: str):
    """Named host-side span, visible inside an active trace.

    Off-TPU builds (or jax versions) without a working TraceAnnotation
    degrade to an inert context manager — caller code stays unconditional
    — and the downgrade is logged once per call site's first failure
    rather than silently swallowed."""
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception as exc:
        get_logger("observe").debug(
            "profiler annotation unavailable off-TPU (%r); %r is a no-op",
            exc, name)
        return contextlib.nullcontext()
