"""Opt-in device profiling (what the reference never had — its JNI scoring
loop was unobservable; diagnosing round 2's throughput swing took manual
probing).

    with mmlspark_tpu.profile("/tmp/trace"):
        model.transform(table)

wraps jax.profiler.trace: the dump is a TensorBoard/Perfetto trace showing
host transfer vs MXU occupancy per step.  `annotate(name)` adds a named span
inside an active trace (jax.profiler.TraceAnnotation) around host-side code
so framework phases (batching, padding, fetch) are visible between device
ops.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def profile(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a device+host trace of the block into `log_dir`."""
    # Probe jax's trace() signature BEFORE entering the block: a TypeError
    # raised by user code inside the block must propagate untouched, never
    # be mistaken for an old-jax signature mismatch.
    kwargs: dict = {}
    try:
        import inspect
        if "profiler_options" in inspect.signature(
                jax.profiler.trace).parameters:
            options = jax.profiler.ProfileOptions()
            options.host_tracer_level = host_tracer_level
            kwargs["profiler_options"] = options
    except Exception:
        pass  # older jax: no options support
    with jax.profiler.trace(log_dir, **kwargs):
        yield log_dir


def annotate(name: str):
    """Named host-side span, visible inside an active trace."""
    return jax.profiler.TraceAnnotation(name)
