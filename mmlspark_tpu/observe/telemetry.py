"""run_telemetry: one context that owns one run's telemetry.

The perf/robustness subsystems each emit fragments — `spans.py`
thread-seconds, `metrics.py` process counters, ad-hoc bench fields — with
no shared event model and no persistent run record.  `run_telemetry(dir)`
unifies them for the dynamic extent of one run:

  * a `Tracer` (observe/trace.py) streaming structured spans/events to
    `<dir>/run.jsonl` as they complete;
  * the run's `PipelineTimings` collector, installed so every existing
    `active_timings()`/`span_on` call site feeds stage attribution into
    THIS run without modification;
  * process counters snapshotted at entry — the run reports DELTAS, so
    two runs in one process (or one test after another) never bleed;
  * gauges: point-in-time samples (`rt.gauge(name, value)`) recorded as
    events and rolled up {last, max, n} — prefetch queue depth/stall
    time, compiled-program cache sizes (the recompile detectors), jax
    device `memory_stats` bytes-in-use/peak (sampled at entry/exit and
    on demand);
  * a final `<dir>/run_summary.json`: wall time, span aggregates,
    counter deltas, gauge rollups, stage attribution + bottleneck
    verdict, memory snapshot.

`dir=None` falls back to MMLSPARK_TPU_TELEMETRY_DIR; when that is unset
too the run records in memory only (ring + summary(), no files).
MMLSPARK_TPU_TELEMETRY=0 is the kill switch: `run_telemetry` blocks
become inert (no collector installed, hot loops keep their zero-cost
fast path), so a suspect 3% can be ruled out in production without a
code change.

Zero-cost when no block is active: `active_run()` is one contextvar
read, and every instrumented hot path gates on it (or on
`active_tracer()`) exactly once per pass.
"""

from __future__ import annotations

import bisect
import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Iterator, Optional

from mmlspark_tpu import config
from mmlspark_tpu.observe.metrics import counters_snapshot
from mmlspark_tpu.observe.spans import PipelineTimings, pipeline_timing
from mmlspark_tpu.observe.trace import DEFAULT_RING, Tracer, tracing

# knobs declared in the one registry (config.py): MMLSPARK_TPU_TELEMETRY
# (kill switch) and MMLSPARK_TPU_TELEMETRY_DIR (default output directory)
TELEMETRY = config.TELEMETRY
TELEMETRY_DIR = config.TELEMETRY_DIR

_active: contextvars.ContextVar[Optional["RunTelemetry"]] = \
    contextvars.ContextVar("mmlspark_tpu_run_telemetry", default=None)

# latency histogram bucket bounds (seconds) shared by every observe_hist
# family — fixed at declaration so counts are O(1) per sample and two
# shards of one fleet always bucket identically (Prometheus `le` is <=,
# so a sample exactly on a bound lands IN that bound's bucket)
HIST_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def telemetry_enabled() -> bool:
    """False only when MMLSPARK_TPU_TELEMETRY is an explicit off value."""
    raw = TELEMETRY.current()
    return str(raw).strip().lower() not in ("0", "off", "false") \
        if raw is not None else True


class RunTelemetry:
    """One run's unified telemetry state (see module docstring).

    `live=False` builds the inert form the kill switch yields: same API,
    nothing recorded, nothing written.
    """

    def __init__(self, run_dir: Optional[str] = None, *, live: bool = True,
                 ring: Optional[int] = None):
        self.dir = run_dir
        self.live = live
        sink = os.path.join(run_dir, "run.jsonl") \
            if (live and run_dir) else None
        self.tracer = Tracer(ring=ring or DEFAULT_RING, sink_path=sink)
        self.timings = PipelineTimings()
        self._counters0 = counters_snapshot() if live else {}
        self._gauges: dict[str, dict] = {}
        # latency histograms (observe_hist): incremental per-bucket counts
        # against the fixed HIST_BUCKETS bounds + sum/count/min/max — O(1)
        # memory per family no matter how many samples, which is what lets
        # the serve hot path record TTFT/inter-token without a ring
        self._hists: dict[str, dict] = {}
        # per-program cost/time tables (observe/costmodel.py): costs from
        # compile-time cost_analysis capture, times accumulated by the hot
        # loops at each execution, keyed (where, program) on both sides so
        # the roofline join is by construction
        self._program_costs: dict[tuple, dict] = {}
        self._program_times: dict[tuple, dict] = {}
        self._program_lock = threading.Lock()
        # the run's recovery timeline (train/supervisor.py): one ordered
        # dict per supervisor event (failure / recover / completed /
        # gave_up ...), surfaced machine-readable in run_summary.json
        self._recovery: list[dict] = []
        # the run's serving timeline (serve/engine.py): admission, shed,
        # degrade, drain decisions in order — the machine-readable account
        # the serve drills assert against
        self._serve: list[dict] = []
        # the run's fleet-routing timeline (serve/router.py): dispatch,
        # failover, ejection, probe re-admission — what the router drills
        # assert their failover/ejection sequences against
        self._routing: list[dict] = []
        # the run's KV-handoff timeline (serve/handoff.py): transfer
        # begin/page/splice/fail events in order — what the disagg drill
        # asserts its re-prefill and cancel-at-splice invariants against
        self._handoff: list[dict] = []
        # the run's prefix-pool timeline (serve/prefix_cache.py via
        # serve/engine.py): hit, insert, evict, evict_refused events in
        # order — what the eviction-under-lease drill asserts against
        self._prefix: list[dict] = []
        # the run's data-service timeline (data/service/dispatcher.py):
        # split dispatch/completion, worker death, re-dispatch, scaling —
        # what the data drill asserts its recovery invariants against
        self._data_service: list[dict] = []
        # the run's population-sweep timeline (train/sweep.py): start,
        # per-rung cull decisions (who, by what metric), per-member final
        # losses, winner — the history store's per-member regression
        # baselines read straight out of this
        self._sweep: list[dict] = []
        # bounded-time cleanups run at finish() (e.g. stopping a metrics
        # server bound to this run) — never allowed to raise or hang the
        # run exit
        self._finalizers: list = []
        self._t0 = time.perf_counter()
        self._finished: Optional[dict] = None
        if live:
            self.tracer._record({
                "type": "run_start", "ts": 0.0,
                "wall_time": self.tracer.wall0, "pid": os.getpid()})
            self.sample_memory(tag="start")

    # -- gauges ----------------------------------------------------------
    def gauge(self, name: str, value, **attrs) -> None:
        """Record one gauge sample: a `gauge` event in the stream plus the
        {last, max, n} rollup the summary and Prometheus exposition read."""
        if not self.live:
            return
        value = float(value)
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = {"last": value, "max": value, "n": 0}
        g["last"] = value
        g["max"] = max(g["max"], value)
        g["n"] += 1
        self.tracer._record({"type": "gauge", "name": name,
                             "ts": round(self.tracer.now(), 6),
                             "value": value, "attrs": attrs})

    def gauges(self) -> dict[str, dict]:
        return {k: dict(v) for k, v in self._gauges.items()}

    # -- latency histograms -----------------------------------------------
    def observe_hist(self, name: str, value) -> None:
        """Record one latency sample into the named histogram family
        (bounded state: per-bucket counts + sum/count/min/max, never raw
        samples).  observe/export.py renders these as cumulative
        Prometheus `_bucket`/`_sum`/`_count` series."""
        if not self.live:
            return
        value = float(value)
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = {
                "counts": [0] * (len(HIST_BUCKETS) + 1),
                "sum": 0.0, "count": 0, "min": value, "max": value}
        h["counts"][bisect.bisect_left(HIST_BUCKETS, value)] += 1
        h["sum"] += value
        h["count"] += 1
        if value < h["min"]:
            h["min"] = value
        elif value > h["max"]:
            h["max"] = value

    def histograms(self) -> dict[str, dict]:
        """{name: {bounds, counts, sum, count, min, max}} — counts are
        per-bucket (NOT cumulative; exposition cumulates), the last slot
        being the +Inf overflow bucket."""
        return {name: {"bounds": list(HIST_BUCKETS),
                       "counts": list(h["counts"]),
                       "sum": round(h["sum"], 6), "count": h["count"],
                       "min": round(h["min"], 6), "max": round(h["max"], 6)}
                for name, h in self._hists.items()}

    def sample_memory(self, tag: str = "sample") -> dict:
        """Gauge each local device's memory_stats bytes_in_use /
        peak_bytes_in_use (no-op fields on backends without the stats —
        the CPU mesh returns nothing; never fabricated)."""
        out: dict[str, float] = {}
        if not self.live:
            return out
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            return out
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            for key in ("bytes_in_use", "peak_bytes_in_use"):
                if key in stats:
                    name = f"memory.device{d.id}.{key}"
                    out[name] = float(stats[key])
                    self.gauge(name, stats[key], tag=tag)
        return out

    # -- per-program cost/time (roofline attribution) ---------------------
    def record_program_cost(self, where: str, program: str,
                            rec: dict) -> None:
        """One compiled program's cost row (capture_program_cost writes
        here; first capture wins — the program's cost never changes)."""
        if not self.live:
            return
        with self._program_lock:
            self._program_costs.setdefault((where, str(program)), dict(rec))

    def add_program_time(self, where: str, program: str, seconds: float,
                         basis: str = "dispatch") -> None:
        """Accumulate one execution's seconds against a program.  `basis`
        says what the seconds measure: 'step_wall' (the span bracketed a
        synced execution — trainer steps) or 'dispatch' (async dispatch
        only — scoring/decode, whose roofline uses the capture probe)."""
        if not self.live:
            return
        with self._program_lock:
            t = self._program_times.setdefault(
                (where, str(program)),
                {"seconds": 0.0, "count": 0, "basis": basis})
            t["seconds"] += seconds
            t["count"] += 1

    def program_summary(self) -> dict:
        """The per-program roofline table (costmodel.program_summary over
        this run's cost/time tables + the device peaks)."""
        from mmlspark_tpu.observe.costmodel import (device_peaks,
                                                    program_summary)
        if not (self._program_costs or self._program_times):
            return {}
        peak_flops, peak_bw = device_peaks()
        with self._program_lock:
            costs = {k: dict(v) for k, v in self._program_costs.items()}
            times = {k: dict(v) for k, v in self._program_times.items()}
        return program_summary(costs, times, peak_flops, peak_bw)

    # -- recovery timeline -------------------------------------------------
    def record_recovery(self, event: dict) -> None:
        """Append one recovery-supervisor event to the run's timeline
        (also streamed as a `recovery` record so run.jsonl replays it);
        the full ordered list lands in run_summary.json under
        `recovery` — the machine-readable account of every rollback,
        skip window, and budget decision the run took."""
        if not self.live:
            return
        rec = dict(event)
        self._recovery.append(rec)
        self.tracer._record({"type": "recovery",
                             "ts": round(self.tracer.now(), 6), **rec})

    # -- serving timeline --------------------------------------------------
    def record_serve(self, event: dict) -> None:
        """Append one serving-engine lifecycle event to the run's ordered
        timeline (also streamed as a `serve` record); the full list lands
        in run_summary.json under `serve` — what the serve chaos drills
        assert their shed/degrade/drain sequences against."""
        if not self.live:
            return
        rec = dict(event)
        self._serve.append(rec)
        self.tracer._record({"type": "serve",
                             "ts": round(self.tracer.now(), 6), **rec})

    def record_routing(self, event: dict) -> None:
        """Append one fleet-routing event (serve/router.py) to the run's
        ordered timeline (also streamed as a `routing` record); the full
        list lands in run_summary.json under `routing` — every dispatch,
        failover, ejection, and probe re-admission, machine-readable."""
        if not self.live:
            return
        rec = dict(event)
        self._routing.append(rec)
        self.tracer._record({"type": "routing",
                             "ts": round(self.tracer.now(), 6), **rec})

    def record_handoff(self, event: dict) -> None:
        """Append one KV-handoff event (serve/handoff.py) to the run's
        ordered timeline (also streamed as a `handoff` record); the full
        list lands in run_summary.json under `handoff` — every transfer
        begin, page rejection, splice, cancel-at-splice, and re-prefill,
        machine-readable for the disagg drill."""
        if not self.live:
            return
        rec = dict(event)
        self._handoff.append(rec)
        self.tracer._record({"type": "handoff",
                             "ts": round(self.tracer.now(), 6), **rec})

    def record_prefix(self, event: dict) -> None:
        """Append one prefix-pool event (serve/prefix_cache.py decisions
        surfaced by serve/engine.py) to the run's ordered timeline (also
        streamed as a `prefix` record); the full list lands in
        run_summary.json under `prefix` — every hit (matched/suffix
        split), insert, eviction, and refused-under-lease eviction,
        machine-readable for the prefix drills."""
        if not self.live:
            return
        rec = dict(event)
        self._prefix.append(rec)
        self.tracer._record({"type": "prefix",
                             "ts": round(self.tracer.now(), 6), **rec})

    def record_sweep(self, event: dict) -> None:
        """Append one population-sweep event (train/sweep.py: start,
        rung cull, member final, winner) to the run's ordered timeline
        (also streamed as a `sweep` record); the full list lands in
        run_summary.json under `sweep`, giving the history store
        per-member curves without any extra plumbing."""
        if not self.live:
            return
        rec = dict(event)
        self._sweep.append(rec)
        self.tracer._record({"type": "sweep",
                             "ts": round(self.tracer.now(), 6), **rec})

    def record_data_service(self, event: dict) -> None:
        """Append one data-service event (data/service/dispatcher.py) to
        the run's ordered timeline (also streamed as a `data_service`
        record); the full list lands in run_summary.json under
        `data_service` — every dispatch, split completion, worker death,
        re-dispatch, scale decision, and snapshot resume — what the data
        drill asserts its no-duplicate/no-drop recovery against."""
        if not self.live:
            return
        rec = dict(event)
        self._data_service.append(rec)
        self.tracer._record({"type": "data_service",
                             "ts": round(self.tracer.now(), 6), **rec})

    # -- finalizers --------------------------------------------------------
    def add_finalizer(self, fn) -> None:
        """Register a cleanup to run at `finish()` (LIFO).  Finalizers
        must themselves be bounded-time (observe/export.py's server stop
        is); a raising finalizer is swallowed — run exit always
        completes."""
        self._finalizers.append(fn)

    # -- counters ---------------------------------------------------------
    def counter_deltas(self) -> dict[str, float]:
        """Counter movement since the block was entered (only counters
        that moved) — the per-run view that stops cross-test bleed."""
        now = counters_snapshot()
        deltas = {k: round(v - self._counters0.get(k, 0.0), 9)
                  for k, v in now.items()}
        return {k: v for k, v in deltas.items() if v}

    # -- finish ------------------------------------------------------------
    def summary(self) -> dict:
        """The run rollup (also written to run_summary.json at exit)."""
        if self._finished is not None:
            return self._finished
        return self._build_summary()

    def _slo_summary(self) -> dict:
        """Per-endpoint SLO compliance + burn rates from the serve and
        routing timelines (observe/slo.py, imported lazily so runs that
        never serve pay nothing).  Never allowed to fail the summary."""
        if not (self._serve or self._routing):
            return {}
        try:
            from mmlspark_tpu.observe.slo import compute_slo
            return compute_slo(self._serve, self._routing,
                               now=self.tracer.now())
        except Exception:
            from mmlspark_tpu.observe.logging import get_logger
            get_logger("observe").warning(
                "SLO rollup failed; omitting `slo` from run summary",
                exc_info=True)
            return {}

    def _build_summary(self) -> dict:
        return {
            "wall_s": round(time.perf_counter() - self._t0, 4),
            "wall_time_start": self.tracer.wall0,
            "counters": self.counter_deltas(),
            "gauges": self.gauges(),
            "histograms": self.histograms(),
            "slo": self._slo_summary(),
            "spans": self.tracer.span_aggregates(),
            "stage_timings": self.timings.summary(),
            "programs": self.program_summary(),
            "recovery": [dict(e) for e in self._recovery],
            "serve": [dict(e) for e in self._serve],
            "routing": [dict(e) for e in self._routing],
            "handoff": [dict(e) for e in self._handoff],
            "prefix": [dict(e) for e in self._prefix],
            "data_service": [dict(e) for e in self._data_service],
            "sweep": [dict(e) for e in self._sweep],
            "trace_records_dropped": self.tracer.dropped,
        }

    def finish(self) -> dict:
        """Seal the run: final memory sample, trailing events (counter
        deltas, stage attribution, run_end), run_summary.json, sink close."""
        if self._finished is not None:
            return self._finished
        while self._finalizers:
            fn = self._finalizers.pop()
            try:
                fn()
            except Exception:  # run exit always completes
                from mmlspark_tpu.observe.logging import get_logger
                get_logger("observe").warning(
                    "run finalizer %r raised; continuing run exit", fn,
                    exc_info=True)
        if not self.live:
            self._finished = {}
            return self._finished
        self.sample_memory(tag="end")
        summary = self._build_summary()
        ts = round(self.tracer.now(), 6)
        for alert in summary.get("slo", {}).get("alerts", []):
            # burn-rate alerts ride the stream too, so run.jsonl replays
            # them without re-deriving the windows
            self.tracer._record({"type": "slo_alert", "ts": ts, **alert})
        self.tracer._record({"type": "counters", "ts": ts,
                             "deltas": summary["counters"]})
        self.tracer._record({"type": "stage_timings", "ts": ts,
                             "seconds": {k: round(v, 6) for k, v in
                                         self.timings.seconds.items()},
                             "summary": summary["stage_timings"]})
        if summary["programs"]:
            # the joined roofline table rides the stream too, so the
            # report CLI renders verdicts from run.jsonl alone
            self.tracer._record({"type": "programs", "ts": ts,
                                 "programs": summary["programs"]})
        self.tracer._record({"type": "run_end", "ts": ts,
                             "wall_s": summary["wall_s"]})
        self.tracer.close()
        if self.dir:
            with open(os.path.join(self.dir, "run_summary.json"), "w") as f:
                json.dump(summary, f, indent=2, sort_keys=True,
                          default=str)
        self._finished = summary
        return summary

    def write_chrome_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Export the ring as Perfetto-loadable trace-event JSON (default:
        <dir>/trace.json when the run has a directory)."""
        if not self.live:
            return None
        if path is None:
            if not self.dir:
                raise ValueError("no path given and the run has no dir")
            path = os.path.join(self.dir, "trace.json")
        return self.tracer.write_chrome_trace(path)


@contextlib.contextmanager
def run_telemetry(run_dir: Optional[str] = None, *,
                  ring: Optional[int] = None) -> Iterator[RunTelemetry]:
    """Own one run's telemetry for the dynamic extent of the block.

        with run_telemetry("/tmp/run1") as rt:
            trainer.fit_arrays(x, y)
            model.transform(table)
        # /tmp/run1/run.jsonl + run_summary.json; rt.summary() in memory

    Nesting installs the inner run for its extent (the outer resumes
    after); the kill switch (MMLSPARK_TPU_TELEMETRY=0) yields an inert
    RunTelemetry so caller code needs no branches.
    """
    if not telemetry_enabled():
        rt = RunTelemetry(None, live=False)
        try:
            yield rt
        finally:
            rt.finish()
        return
    run_dir = run_dir if run_dir is not None else TELEMETRY_DIR.current()
    if run_dir:
        run_dir = os.path.abspath(os.path.expanduser(str(run_dir)))
        os.makedirs(run_dir, exist_ok=True)
    rt = RunTelemetry(run_dir, ring=ring)
    token = _active.set(rt)
    try:
        with tracing(rt.tracer), pipeline_timing(rt.timings):
            yield rt
    finally:
        _active.reset(token)
        rt.finish()


def active_run() -> Optional[RunTelemetry]:
    """The ambient run, or None — the hot-loop fast-path check (capture
    ONCE on the consumer thread; worker threads have their own context)."""
    return _active.get()
