"""Observability: logger factory, typed metric contract, stage timers, and
device profiling (reference Logging.scala:14-23 + Metrics.scala:37-47 +
TestBase.scala:138-153; the profiler is TPU-native headroom)."""

from mmlspark_tpu.observe.logging import LOG_ROOT, get_logger
from mmlspark_tpu.observe.metrics import (MetricData, counters_metric_data,
                                          counters_snapshot, get_counter,
                                          inc_counter, reset_counters)
from mmlspark_tpu.observe.profiler import annotate, profile
from mmlspark_tpu.observe.spans import (PipelineTimings, active_timings,
                                        pipeline_timing, span_on)
from mmlspark_tpu.observe.timing import (StageTimings, instrument_stage_method,
                                         stage_timing)

__all__ = ["LOG_ROOT", "get_logger", "MetricData", "annotate", "profile",
           "StageTimings", "instrument_stage_method", "stage_timing",
           "PipelineTimings", "active_timings", "pipeline_timing", "span_on",
           "inc_counter", "get_counter", "counters_snapshot",
           "reset_counters", "counters_metric_data"]
