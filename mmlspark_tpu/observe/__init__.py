"""Observability: logger factory, typed metric contract, stage timers,
device profiling, and the unified telemetry subsystem — structured run
traces (trace.py), the run_telemetry run record (telemetry.py),
Prometheus export (export.py), the run-report diagnostic (report.py),
and the analytics layer that interprets it all: per-program roofline
attribution (costmodel.py), numerics health probes (numerics.py), and
the persistent bench-history regression store (history.py).
Reference Logging.scala:14-23 + Metrics.scala:37-47 + TestBase.scala:
138-153; everything past the loggers is TPU-native headroom."""

from mmlspark_tpu.observe.costmodel import (capture_program_cost,
                                            costmodel_enabled, roofline)
from mmlspark_tpu.observe.logging import LOG_ROOT, get_logger
from mmlspark_tpu.observe.numerics import (LossSpikeDetector,
                                           NonFiniteError, tree_health)
from mmlspark_tpu.observe.metrics import (MetricData, counters_metric_data,
                                          counters_snapshot, get_counter,
                                          inc_counter, reset_counters)
from mmlspark_tpu.observe.export import (prometheus_text, serve_metrics,
                                         write_metrics)
from mmlspark_tpu.observe.profiler import annotate, profile
from mmlspark_tpu.observe.spans import (PipelineTimings, active_timings,
                                        pipeline_timing, span_on)
from mmlspark_tpu.observe.telemetry import (RunTelemetry, active_run,
                                            run_telemetry)
from mmlspark_tpu.observe.timing import (StageTimings, instrument_stage_method,
                                         stage_timing)
from mmlspark_tpu.observe.assemble import (assemble, assemble_dir,
                                           load_shard_set, tracez_payload)
from mmlspark_tpu.observe.slo import compute_slo
from mmlspark_tpu.observe.trace import (Span, TraceContext, Tracer,
                                        active_tracer, current_span_id,
                                        head_sampled, mint_context,
                                        new_trace_id, tail_promote,
                                        trace_event, trace_span)

__all__ = ["LOG_ROOT", "get_logger", "MetricData", "annotate", "profile",
           "StageTimings", "instrument_stage_method", "stage_timing",
           "PipelineTimings", "active_timings", "pipeline_timing", "span_on",
           "inc_counter", "get_counter", "counters_snapshot",
           "reset_counters", "counters_metric_data",
           "Span", "Tracer", "active_tracer", "current_span_id",
           "trace_event", "trace_span",
           "TraceContext", "mint_context", "new_trace_id", "head_sampled",
           "tail_promote", "compute_slo",
           "assemble", "assemble_dir", "load_shard_set", "tracez_payload",
           "RunTelemetry", "active_run", "run_telemetry",
           "prometheus_text", "serve_metrics", "write_metrics",
           "capture_program_cost", "costmodel_enabled", "roofline",
           "LossSpikeDetector", "NonFiniteError", "tree_health"]
