"""Namespaced logger factory (reference Logging.scala:14-23).

The reference constructs every logger as `<configured root>.<suffix>` through
one factory so the whole framework is silenceable/redirectable from a single
knob.  Same here: `get_logger("ml.statistics")` -> logger
"mmlspark_tpu.ml.statistics", with the root level driven by the
MMLSPARK_TPU_LOG_LEVEL variable of the mmlspark_tpu.config registry.
"""

from __future__ import annotations

import logging

LOG_ROOT = "mmlspark_tpu"
_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    from mmlspark_tpu import config
    root = logging.getLogger(LOG_ROOT)
    level = config.LOG_LEVEL.current()
    if level is not None:
        # the user asked the framework to manage its own output: set the
        # level and attach a handler so records print without propagating
        # twice through an application root
        root.setLevel(getattr(logging, level.upper(), logging.WARNING))
        if not any(isinstance(h, logging.StreamHandler)
                   for h in root.handlers):
            handler = logging.StreamHandler()
            handler.setFormatter(logging.Formatter(
                "%(asctime)s %(name)s %(levelname)s: %(message)s"))
            root.addHandler(handler)
            root.propagate = False
    # otherwise: normal library behavior — no handlers, propagation on,
    # the application's logging config decides what shows
    _configured = True


def get_logger(suffix: str = "") -> logging.Logger:
    """The canonical logger for a subsystem: one per package, named under
    the framework root (`get_logger("train")` -> "mmlspark_tpu.train")."""
    _configure_root()
    name = f"{LOG_ROOT}.{suffix}" if suffix else LOG_ROOT
    return logging.getLogger(name)
