"""Stitch per-process telemetry shards into per-request trace waterfalls.

Every tier of a fleet — router, prefill replicas, the handoff bus, decode
replicas, data-service workers — streams its own run.jsonl shard with its
own span-id space (per-tracer counters: ids COLLIDE across shards and
across two runs in one process).  The only cross-shard join key is the
TraceContext trace id (observe/trace.py) that each traced record carries.
This module does the join:

  * `load_shard_set` reads a directory (or explicit path list) of JSONL
    shards, torn-tail tolerant per file; a missing or unreadable shard
    becomes a degraded note — never a raise — so a crashed worker still
    yields a report;
  * `assemble` groups trace-carrying records by trace id and replays
    each group into a waterfall of CONTIGUOUS stage segments (queue →
    prefill → handoff → decode), closed by the fleet-level finish.
    Contiguity is the point: stage durations sum to the end-to-end wall
    by construction, so attribution is never "percentages of something
    else".  Stage transitions come from the timeline events the serving
    stack already records (admit/dispatch/failover, kv begin/spliced,
    join, finish); a failover re-opens the queue stage, and every
    dispatch attempt is kept so one trace id shows both the failed and
    the byte-exact retried attempt;
  * records whose trace id has no root `admit` anywhere in the shard set
    (parent shard lost, torn stream) land in an orphan quarantine keyed
    by trace id — counted and inspectable, never silently dropped and
    never able to corrupt a real waterfall;
  * sampling: head-sampled or tail-promoted traces keep their full
    segment/timeline detail; the rest keep only the stage rollup, which
    is what holds tracing under the overhead pin at high request rates.

`tracez_payload` runs the same assembly over the live ring for the
`/tracez` endpoint and report.py's `requests` section.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Iterable, Optional

# (record type, event) pairs that open a new waterfall stage; everything
# between two transitions is attributed to the stage the first one opened
_STAGE_OPEN = {
    ("routing", "admit"): "queue",
    ("routing", "dispatch"): "prefill",
    ("routing", "failover"): "queue",     # re-queued at head
    ("serve", "admit"): "queue",          # bare engine (no router tier)
    ("serve", "join"): "decode",          # colocated seat
    ("serve", "remote_join"): "decode",   # decode-tier splice seat
    ("handoff", "begin"): "handoff",
    ("handoff", "spliced"): "decode",
    ("data_service", "admit"): "data_service",
}

_FINISH = {("routing", "finish"), ("serve", "finish"),
           ("data_service", "finish")}


def parse_jsonl(path: str) -> tuple[list[dict], Optional[str]]:
    """One shard file → (records, degraded note or None).  A torn final
    line (the writer died mid-record) is expected and silently dropped;
    corruption ANYWHERE else is surfaced in the note."""
    try:
        with open(path, "r") as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [], f"unreadable shard {path}: {e}"
    records, bad = [], 0
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn tail
            bad += 1
            continue
        if isinstance(rec, dict):
            records.append(rec)
    note = f"{bad} corrupt mid-file line(s) in {path}" if bad else None
    return records, note


def load_shard_set(source) -> dict:
    """Read a shard set — a directory (every *.jsonl under it) or an
    explicit iterable of paths — into one record list.

    Each record is tagged `_shard` with its shard's identity, taken from
    the shard's run_start record (pid + wall-clock start): pids recycle
    and span ids restart per tracer, so (pid, wall_time) is what keeps
    two runs in one process distinguishable.  Returns {records, shards,
    degraded}; missing shards degrade, they never raise."""
    if isinstance(source, (str, os.PathLike)):
        root = os.fspath(source)
        if os.path.isdir(root):
            paths = sorted(glob.glob(os.path.join(root, "**", "*.jsonl"),
                                     recursive=True))
            degraded = [] if paths else [f"no shards under {root}"]
        else:
            paths, degraded = [], [f"missing shard dir {root}"]
    else:
        paths, degraded = [os.fspath(p) for p in source], []
    records, shards = [], []
    for path in paths:
        if not os.path.exists(path):
            degraded.append(f"missing shard {path}")
            continue
        recs, note = parse_jsonl(path)
        if note:
            degraded.append(note)
        key = path
        for r in recs:
            if r.get("type") == "run_start":
                key = f"{r.get('pid')}:{r.get('wall_time')}"
                break
        for r in recs:
            r["_shard"] = key
        shards.append({"path": path, "shard": key, "records": len(recs)})
        records.extend(recs)
    return {"records": records, "shards": shards, "degraded": degraded}


def _what(rec: dict) -> Optional[str]:
    """The record's event name: serving timelines use `event`,
    data-service ones use `kind`."""
    return rec.get("event") or rec.get("kind")


def _trace_ids(rec: dict) -> list[str]:
    """Every trace id a record carries: the timeline records put `trace`
    at top level, spans/events put it in attrs, batch-level records
    (prefill chunks) carry a `traces` list."""
    out = []
    attrs = rec.get("attrs") if isinstance(rec.get("attrs"), dict) else {}
    for v in (rec.get("trace"), attrs.get("trace")):
        if isinstance(v, str) and v:
            out.append(v)
    for v in (rec.get("traces"), attrs.get("traces")):
        if isinstance(v, (list, tuple)):
            out.extend(t for t in v if isinstance(t, str) and t)
    return out


def _waterfall(tid: str, recs: list[dict]) -> dict:
    """Replay one trace's records (ts order) into contiguous stage
    segments.  See module docstring for the contiguity argument."""
    recs = sorted(recs, key=lambda r: float(r.get("ts", 0.0) or 0.0))
    admit = next(r for r in recs
                 if _what(r) == "admit")  # caller guarantees one
    finishes = [r for r in recs if (r.get("type"), _what(r))
                in _FINISH]
    # the fleet-level routing finish outranks per-attempt engine ones
    terminal = next((r for r in finishes if r.get("type") == "routing"),
                    finishes[-1] if finishes else None)
    t_admit = float(admit.get("ts", 0.0) or 0.0)
    t_end = float(terminal.get("ts", t_admit)) if terminal \
        else float(recs[-1].get("ts", t_admit) or t_admit)
    segments: list[dict] = []
    stage, t_open, attempt = None, t_admit, 1
    for rec in recs:
        ts = float(rec.get("ts", 0.0) or 0.0)
        if terminal is not None and ts > t_end:
            break
        key = (rec.get("type"), _what(rec))
        nxt = _STAGE_OPEN.get(key)
        if nxt is None:
            continue
        if key == ("routing", "dispatch"):
            try:
                attempt = max(attempt, int(rec.get("attempt", attempt)))
            except (TypeError, ValueError):
                pass
        if stage is not None and ts > t_open:
            segments.append({"stage": stage, "t0": round(t_open, 6),
                             "t1": round(ts, 6),
                             "dur": round(ts - t_open, 6),
                             "attempt": attempt})
        stage, t_open = nxt, max(ts, t_open)
    if stage is not None and t_end > t_open:
        segments.append({"stage": stage, "t0": round(t_open, 6),
                         "t1": round(t_end, 6),
                         "dur": round(t_end - t_open, 6),
                         "attempt": attempt})
    stages: dict[str, float] = {}
    for seg in segments:
        stages[seg["stage"]] = round(
            stages.get(seg["stage"], 0.0) + seg["dur"], 6)
    sampled = bool(admit.get("sampled", True))
    tail = (terminal or {}).get("tail")
    wf = {
        "trace": tid,
        "wall_s": round(t_end - t_admit, 6),
        "status": (terminal or {}).get("status"),
        "lane": admit.get("priority") or (terminal or {}).get("priority"),
        "attempts": attempt,
        "sampled": sampled,
        "tail": tail,
        "stages": stages,
        "stages_sum_s": round(sum(stages.values()), 6),
        "records": len(recs),
        "degraded": terminal is None,
    }
    if sampled or tail:
        # full detail only for head-sampled or tail-promoted traces
        wf["segments"] = segments
        wf["timeline"] = [
            {"ts": round(float(r.get("ts", 0.0) or 0.0), 6),
             "type": r.get("type"),
             "what": _what(r) or r.get("name"),
             **({"shard": r["_shard"]} if "_shard" in r else {})}
            for r in recs]
    return wf


def assemble(records: Iterable[dict],
             degraded: Optional[list] = None) -> dict:
    """Records (any mix of shards, any order) → {waterfalls, orphans,
    degraded}.  Waterfalls sort slowest-first — the /tracez contract."""
    by_trace: dict[str, list[dict]] = {}
    for rec in records:
        for tid in _trace_ids(rec):
            by_trace.setdefault(tid, []).append(rec)
    waterfalls, orphans = [], {}
    for tid, recs in by_trace.items():
        if any(_what(r) == "admit" for r in recs):
            waterfalls.append(_waterfall(tid, recs))
        else:
            tss = [float(r.get("ts", 0.0) or 0.0) for r in recs]
            orphans[tid] = {
                "records": len(recs),
                "types": sorted({str(r.get("type")) for r in recs}),
                "shards": sorted({r["_shard"] for r in recs
                                  if "_shard" in r}),
                "first_ts": round(min(tss), 6) if tss else None,
                "last_ts": round(max(tss), 6) if tss else None,
            }
    waterfalls.sort(key=lambda w: w["wall_s"], reverse=True)
    return {"waterfalls": waterfalls, "orphans": orphans,
            "degraded": list(degraded or [])}


def assemble_dir(source) -> dict:
    """load_shard_set + assemble in one call (report.py's entry point)."""
    shard_set = load_shard_set(source)
    out = assemble(shard_set["records"], degraded=shard_set["degraded"])
    out["shards"] = shard_set["shards"]
    return out


def tracez_payload(run, top: int = 10) -> dict:
    """The /tracez response: slowest assembled waterfalls from the live
    run's ring (every timeline record is in the ring, so no file I/O on
    the serving path)."""
    if run is None:
        return {"error": "no active telemetry run", "requests": []}
    out = assemble(run.tracer.records())
    return {
        "total": len(out["waterfalls"]),
        "orphans": len(out["orphans"]),
        "requests": out["waterfalls"][:max(0, int(top))],
    }
