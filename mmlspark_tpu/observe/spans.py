"""Stage-attributed pipeline timing: where does a batch's wall time go?

`observe/timing.py` answers "which pipeline STAGE is slow" (fit/transform
per Transformer).  This module answers the finer question the overlapped
data pipeline raises: within one scoring/training loop, how much total
thread-time went to each PIPELINE PHASE —

    host      decode / np.stack / pad / mask build (CPU-side staging)
    transfer  host->HBM device_put (the PCIe/tunnel link)
    compute   jitted dispatch of the model step
    drain     blocking device->host fetch of results

— and which phase is the bottleneck.  Autoregressive generation adds its
own two phases (GENERATE_STAGES, recorded by the decode engine in
models/generate.py): `prefill` (the prompt forward that writes the KV
cache) and `decode` (the windowed per-token segments, including their
between-segment early-exit checks).  They ride the same collector and
show up in `summary()` as stage_prefill_s / stage_decode_s whenever a
`pipeline_timing()` block wraps a TextGenerator.transform — the split
that tells prompt-bound serving apart from generation-bound serving.  Spans are recorded from both the
consumer thread and the prefetcher's staging workers (thread-safe), so
overlapped phases each report their full cost: totals are thread-seconds,
not wall, and under a healthy pipeline their sum EXCEEDS wall time —
that excess is exactly the overlap the prefetcher buys.

Zero-cost when inactive (the `stage_timing` pattern): hot loops call
`active_timings()` once per pass and skip span bookkeeping entirely when
no `pipeline_timing()` block is active.  Worker threads never see the
consumer's contextvars, so collectors are captured ONCE on the consumer
thread and passed explicitly into staging closures via `span_on`.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Iterator, Optional

STAGES = ("host", "transfer", "compute", "drain")
# generation phases (models/generate.py DecodeEngine); reported by
# summary() only when recorded, so scoring/training summaries stay 4-stage
GENERATE_STAGES = ("prefill", "decode")

_collector: contextvars.ContextVar[Optional["PipelineTimings"]] = \
    contextvars.ContextVar("mmlspark_tpu_pipeline_timings", default=None)


class PipelineTimings:
    """Thread-safe per-phase accumulated seconds + batch counts."""

    def __init__(self):
        self._lock = threading.Lock()
        self.seconds: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    def record(self, stage: str, seconds: float) -> None:
        with self._lock:
            self.seconds[stage] = self.seconds.get(stage, 0.0) + seconds
            self.counts[stage] = self.counts.get(stage, 0) + 1

    @contextlib.contextmanager
    def span(self, stage: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(stage, time.perf_counter() - t0)

    def bottleneck(self) -> Optional[str]:
        """The phase with the largest accumulated thread-time.

        Under full overlap the pipeline's throughput is set by its slowest
        stage (the classic pipeline law) — this names it.
        """
        if not self.seconds:
            return None
        return max(self.seconds, key=lambda k: self.seconds[k])

    def summary(self) -> dict:
        """The bench/report schema: stage_<phase>_s fields + the verdict."""
        out = {f"stage_{s}_s": round(self.seconds.get(s, 0.0), 4)
               for s in STAGES}
        for s in sorted(set(self.seconds) - set(STAGES)):
            out[f"stage_{s}_s"] = round(self.seconds[s], 4)
        out["bottleneck"] = self.bottleneck()
        return out

    def __str__(self):
        parts = [f"{s}={self.seconds.get(s, 0.0):.3f}s" for s in STAGES]
        return f"PipelineTimings({', '.join(parts)}, " \
               f"bottleneck={self.bottleneck()})"


@contextlib.contextmanager
def pipeline_timing(timings: Optional[PipelineTimings] = None
                    ) -> Iterator[PipelineTimings]:
    """Collect per-phase spans for the dynamic extent of the block.

        with pipeline_timing() as spans:
            model.transform(table)
        print(spans.summary())   # {'stage_host_s': ..., 'bottleneck': ...}

    `timings` installs an EXISTING collector instead of a fresh one —
    how run_telemetry (observe/telemetry.py) owns the run's stage
    attribution while the hot loops keep recording through the same
    `active_timings()` fast path.
    """
    timings = timings if timings is not None else PipelineTimings()
    token = _collector.set(timings)
    try:
        yield timings
    finally:
        _collector.reset(token)


def active_timings() -> Optional[PipelineTimings]:
    """The ambient collector, or None — capture on the CONSUMER thread and
    pass into staging closures (worker threads have their own context)."""
    return _collector.get()


def monotonic() -> float:
    """The sanctioned hot-loop clock (monotonic seconds).

    scripts/lint.py forbids raw `time.time()`/`time.perf_counter()` calls
    in hot-loop modules: fine-grained timing there must ride the span
    machinery (so it is attributed and exported), and the few coarse wall
    fields that remain (epoch wall_s in the training history) read this
    one clock — a single seam instead of scattered raw timer calls.
    """
    return time.perf_counter()


@contextlib.contextmanager
def span_on(timings: Optional[PipelineTimings], stage: str) -> Iterator[None]:
    """Span against a captured collector; no-op (and near-free) for None."""
    if timings is None:
        yield
        return
    with timings.span(stage):
        yield
