"""Metrics export: Prometheus text exposition over counters/gauges/spans.

The TensorFlow system paper (PAPERS.md) credits built-in metrics — not
bolt-on profiling — for production viability; this is the pull surface:
`prometheus_text()` renders the process counters (observe/metrics.py),
the active run's gauges, its per-span-name aggregates, and its stage
attribution as Prometheus text exposition format (version 0.0.4), so a
run is scrapeable by any standard collector.

Two pull transports, both dependency-free:

  * `write_metrics(path)` — file pull (node_exporter textfile-collector
    style: a cron/sidecar ships the file);
  * `serve_metrics(port)` — a stdlib-only `http.server` thread answering
    GET /metrics; returns the server (its bound port at
    `server.server_address[1]`, stop with `server.shutdown()`).

Metric naming: every name is prefixed `mmlspark_tpu_`, sanitized to the
Prometheus charset, counters suffixed `_total`.  Counter values are the
process-wide ABSOLUTE tallies (Prometheus counters are cumulative by
contract; rate() handles resets) — per-run deltas live in
run_summary.json, not here.
"""

from __future__ import annotations

import re
import threading
from typing import Optional

from mmlspark_tpu.observe.metrics import counters_snapshot
from mmlspark_tpu.observe.telemetry import RunTelemetry, active_run

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
PREFIX = "mmlspark_tpu"


def _metric_name(name: str, suffix: str = "") -> str:
    base = _NAME_RE.sub("_", name.strip())
    if base and base[0].isdigit():
        base = "_" + base
    return f"{PREFIX}_{base}{suffix}"


def _label_value(value) -> str:
    """Escape a label value per the exposition grammar (backslash, quote,
    newline) — program keys carry shape tuples like '(16, 32, 3):uint8'."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt(value: float) -> str:
    value = float(value)
    return repr(int(value)) if value == int(value) else repr(value)


def prometheus_text(run: Optional[RunTelemetry] = None) -> str:
    """The exposition document.  `run` defaults to the ambient
    run_telemetry block; with no run active, counters alone are exposed
    (they are process-wide and always meaningful)."""
    run = run if run is not None else active_run()
    lines: list[str] = []

    counters = counters_snapshot()
    for name in sorted(counters):
        metric = _metric_name(name, "_total")
        lines.append(f"# HELP {metric} mmlspark_tpu process counter "
                     f"{name!r}")
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {_fmt(counters[name])}")

    # circuit-breaker state per endpoint (resilience/breaker.py): live
    # process-wide snapshots like the counters, so a tripped breaker is
    # visible to a scraper whether or not a run block is active.
    # state codes: 0 closed, 1 half-open, 2 open (breaker.STATE_CODES)
    from mmlspark_tpu.resilience.breaker import breakers_snapshot
    breakers = breakers_snapshot()
    if breakers:
        state = _metric_name("breaker_state")
        retry = _metric_name("breaker_retry_in_s")
        fails = _metric_name("breaker_consecutive_failures")
        lines.append(f"# HELP {state} circuit-breaker state per endpoint "
                     f"(0=closed, 1=half-open, 2=open)")
        lines.append(f"# TYPE {state} gauge")
        for ep in sorted(breakers):
            lines.append(f'{state}{{endpoint="{_label_value(ep)}"}} '
                         f"{_fmt(breakers[ep]['state_code'])}")
        lines.append(f"# HELP {retry} seconds until the endpoint's next "
                     f"half-open probe is allowed (0 when closed/due)")
        lines.append(f"# TYPE {retry} gauge")
        for ep in sorted(breakers):
            lines.append(f'{retry}{{endpoint="{_label_value(ep)}"}} '
                         f"{_fmt(breakers[ep]['retry_in_s'])}")
        lines.append(f"# HELP {fails} consecutive failures recorded "
                     f"against the endpoint")
        lines.append(f"# TYPE {fails} gauge")
        for ep in sorted(breakers):
            lines.append(
                f'{fails}{{endpoint="{_label_value(ep)}"}} '
                f"{_fmt(breakers[ep]['consecutive_failures'])}")

    if run is not None and run.live:
        # latency histograms (RunTelemetry.observe_hist): real Prometheus
        # histogram families — cumulative `le` buckets (a sample counts in
        # ITS bucket and every larger one, closing with +Inf == _count),
        # plus _sum/_count, the shape rate()/histogram_quantile() expect
        for name, h in sorted(run.histograms().items()):
            metric = _metric_name(name + "_seconds")
            lines.append(f"# HELP {metric} mmlspark_tpu latency "
                         f"histogram {name!r} (seconds)")
            lines.append(f"# TYPE {metric} histogram")
            cum = 0
            for bound, count in zip(h["bounds"], h["counts"]):
                cum += count
                lines.append(f'{metric}_bucket{{le="{_fmt(bound)}"}} '
                             f"{cum}")
            cum += h["counts"][-1]
            lines.append(f'{metric}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{metric}_sum {_fmt(h['sum'])}")
            lines.append(f"{metric}_count {h['count']}")

        for name, g in sorted(run.gauges().items()):
            metric = _metric_name(name)
            lines.append(f"# HELP {metric} mmlspark_tpu run gauge "
                         f"{name!r} (last sample; _max variant below)")
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {_fmt(g['last'])}")
            lines.append(f"{metric}_max {_fmt(g['max'])}")

        agg = run.tracer.span_aggregates()
        if agg:
            secs = _metric_name("span_seconds", "_total")
            cnt = _metric_name("span", "_total")
            lines.append(f"# HELP {secs} total seconds per span name")
            lines.append(f"# TYPE {secs} counter")
            for name in sorted(agg):
                lines.append(f'{secs}{{name="{name}"}} '
                             f"{_fmt(agg[name]['total_s'])}")
            lines.append(f"# HELP {cnt} span count per span name")
            lines.append(f"# TYPE {cnt} counter")
            for name in sorted(agg):
                lines.append(f'{cnt}{{name="{name}"}} '
                             f"{_fmt(agg[name]['count'])}")

        progs = run.program_summary()
        if progs:
            # the roofline gauges (observe/costmodel.py): one sample per
            # compiled program, labeled by call site + shape-class key.
            # Every metric name gets its # HELP/# TYPE metadata once —
            # the exposition-grammar test covers these lines too.
            fields = (
                ("program_mfu", "mfu",
                 "model-FLOPs utilization per compiled program "
                 "(achieved FLOP/s over the chip bf16 peak)"),
                ("program_hbm_bw_util", "hbm_bw_util",
                 "HBM-bandwidth utilization per compiled program "
                 "(achieved bytes/s over the chip HBM peak)"),
                ("program_step_seconds", "step_s",
                 "per-execution seconds of one compiled program "
                 "(span wall or capture probe; see step_basis)"),
                ("program_flops", "flops",
                 "FLOPs per execution of one compiled program "
                 "(XLA cost_analysis at compile time)"),
                ("program_bytes_accessed", "bytes_accessed",
                 "bytes accessed per execution of one compiled program "
                 "(XLA cost_analysis at compile time)"),
            )
            for metric_base, field, help_text in fields:
                samples = [(key, p[field]) for key, p in sorted(
                    progs.items()) if p.get(field) is not None]
                if not samples:
                    continue
                metric = _metric_name(metric_base)
                lines.append(f"# HELP {metric} {help_text}")
                lines.append(f"# TYPE {metric} gauge")
                for key, value in samples:
                    p = progs[key]
                    lines.append(
                        f'{metric}{{where="{_label_value(p["where"])}",'
                        f'program="{_label_value(p["program"])}"}} '
                        f"{_fmt(value)}")

        if run.timings.seconds:
            stage = _metric_name("stage_seconds", "_total")
            lines.append(f"# HELP {stage} thread-seconds per pipeline "
                         f"stage (observe/spans.py)")
            lines.append(f"# TYPE {stage} counter")
            for name in sorted(run.timings.seconds):
                lines.append(f'{stage}{{stage="{name}"}} '
                             f"{_fmt(run.timings.seconds[name])}")

    return "\n".join(lines) + "\n"


def write_metrics(path: str, run: Optional[RunTelemetry] = None) -> str:
    """File-pull exposition (textfile-collector style)."""
    with open(path, "w") as f:
        f.write(prometheus_text(run))
    return path


def stop_server(server, timeout_s: float = 2.0) -> bool:
    """Stop an HTTP server started here within a bounded time.

    `HTTPServer.shutdown()` blocks until the serve_forever loop notices —
    normally milliseconds, but a wedged handler (a hung client mid-write)
    can hold it arbitrarily; this calls it from a reaper thread and waits
    at most `timeout_s` before closing the listening socket regardless,
    so a telemetry exit (or a graceful drain) is never held hostage by
    one stuck connection.  Returns True when the loop confirmed shutdown
    inside the budget."""
    stopper = threading.Thread(target=server.shutdown, daemon=True,
                               name="mmlspark-metrics-stop")
    stopper.start()
    stopper.join(timeout_s)
    clean = not stopper.is_alive()
    if not clean:
        from mmlspark_tpu.observe.logging import get_logger
        get_logger("observe.export").warning(
            "metrics server did not confirm shutdown within %.1fs; "
            "closing its socket anyway", timeout_s)
    server.server_close()
    return clean


def serve_metrics(port: int = 0, host: str = "127.0.0.1",
                  run: Optional[RunTelemetry] = None):
    """Serve GET /metrics on a daemon thread (stdlib http.server only).

    `run` is captured HERE, on the caller's thread: the server thread
    never sees the caller's contextvars (the same capture-by-closure rule
    as spans.py), so the ambient run must be bound at call time.  When a
    live run is bound, the server registers a run finalizer so the
    run_telemetry exit stops it with `stop_server`'s bounded wait —
    a run block never leaks its scrape port.

    Unknown paths get a 404 and errors carry an explicit text/plain
    Content-Type (BaseHTTPRequestHandler's default error page is HTML —
    wrong for a metrics port whose only consumers speak plain text).
    Returns the HTTPServer; port 0 binds an ephemeral port (read it back
    from `server.server_address[1]`), `stop_server(server)` (or
    `server.shutdown()`) stops it.
    """
    import http.server

    run = run if run is not None else active_run()

    class Handler(http.server.BaseHTTPRequestHandler):
        # explicit Content-Type on every error response (404s included)
        error_content_type = "text/plain; charset=utf-8"
        error_message_format = "%(code)d %(message)s\n"

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_error(404, "unknown path (try /metrics)")
                return
            body = prometheus_text(run).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            from mmlspark_tpu.observe.logging import get_logger
            get_logger("observe.export").debug(fmt, *args)

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="mmlspark-metrics")
    thread.start()
    if run is not None and run.live:
        run.add_finalizer(lambda: stop_server(server))
    return server
