"""Per-program cost capture + roofline attribution.

PR 5 made every hot path *measurable* (spans, counters, run records);
this module makes the measurements *interpretable*.  BENCH_r05 is the
motivating read: resnet50 end-to-end MFU 0.0056 against 0.46 on-device —
two numbers, no verdict.  The missing piece is per-program cost: XLA's
compiled `cost_analysis()` knows exactly how many FLOPs and HBM bytes
each compiled program moves, and the telemetry layer already knows how
long each execution took.  Joining the two yields, for every compiled
program the run paid for:

  * **MFU** — achieved FLOP/s over the chip's bf16 peak
    (`utils/perf.device_peak_flops`);
  * **HBM-bandwidth utilization** — achieved bytes/s over the chip's HBM
    peak (`utils/perf.device_peak_hbm_bw`);
  * a **roofline verdict** — the program's arithmetic intensity against
    the chip's ridge point names its ceiling (compute vs bandwidth), and
    its achieved fraction of that ceiling tells whether the program ever
    gets near it: a program far below BOTH ceilings is not the
    bottleneck — the host is (`host-bound`, exactly BENCH_r05's resnet
    end-to-end story).

Capture rides the recompile detectors PR 5 installed: the moment a hot
loop registers a NEW shape class (TPUModel batch shapes, Trainer's train
step, DecodeEngine prefill/segment programs), `capture_program_cost`
AOT-lowers the same jitted callable at the same arguments and reads
`compiled.cost_analysis()` — once per program per hot-loop lifetime,
never in the steady state.  The hot loops remember each returned cost
row and replay it (`RunTelemetry.record_program_cost`, idempotent) into
every later `run_telemetry` block, so a warm model/engine's steady-state
runs still get roofline rows without paying a fresh capture.  The capture costs one extra XLA compile (plus, when
`probe=True`, one synced execution that yields a clean per-program step
time on paths whose live spans wall only the async dispatch).  Backends
without a cost model (and any capture failure at all) degrade to a
logged no-op: the run proceeds, the program simply has no cost row.

MMLSPARK_TPU_COSTMODEL=0 switches capture off without touching the rest
of telemetry (the mirror of the MMLSPARK_TPU_TELEMETRY kill switch).
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

from mmlspark_tpu import config
from mmlspark_tpu.observe.logging import get_logger

COSTMODEL = config.register(
    "MMLSPARK_TPU_COSTMODEL", default=None,
    doc="Per-program cost capture kill switch: '0'/'off'/'false' skips "
        "the compile-time cost_analysis() capture (and its one-off AOT "
        "compile per program) while the rest of telemetry stays live "
        "(observe/costmodel.py).")

# below this fraction of the binding ceiling, the program is not what
# bounds the run — something outside it (the host pipeline) is
HOST_BOUND_FLOOR = 0.05


def costmodel_enabled() -> bool:
    """False only when MMLSPARK_TPU_COSTMODEL is an explicit off value."""
    raw = COSTMODEL.current()
    return str(raw).strip().lower() not in ("0", "off", "false") \
        if raw is not None else True


def extract_cost(compiled) -> Optional[dict]:
    """{'flops', 'bytes_accessed'} from a Compiled's cost_analysis(), or
    None when the backend provides no cost model (never raises)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        byts = cost.get("bytes accessed")
        if not flops and not byts:
            return None
        return {"flops": float(flops) if flops else None,
                "bytes_accessed": float(byts) if byts else None}
    except Exception:
        return None


def capture_program_cost(fn, args: Sequence[Any], *, where: str,
                         program: str, run=None, probe: bool = False,
                         static_argnums: Sequence[int] = ()) -> Optional[dict]:
    """Capture one compiled program's cost row into the active run.

    `fn` is the jitted callable the hot loop is about to execute (or just
    executed) at `args`; `program` is the hot loop's own shape-class key —
    the SAME key its spans and recompile events carry, so the join is by
    construction.  `probe=True` additionally executes the AOT-compiled
    program once, synced, for a clean per-program step time (used by the
    scoring/decode paths, whose live spans wall only the async dispatch;
    never probe a donating function — its buffers would be consumed).

    Every failure — no cost model on this backend, an AOT lowering quirk,
    anything — is a logged no-op: capture is diagnostics, and diagnostics
    never take down a run.
    """
    from mmlspark_tpu.observe.telemetry import active_run
    run = run if run is not None else active_run()
    if run is None or not run.live or not costmodel_enabled():
        return None
    program = str(program)
    try:
        compiled = fn.lower(*args).compile()
        rec = extract_cost(compiled)
        if rec is None:
            raise ValueError("backend reports no cost model")
        if probe:
            call_args = [a for i, a in enumerate(args)
                         if i not in set(static_argnums)]
            out = compiled(*call_args)
            t0 = time.perf_counter()
            out = compiled(*call_args)
            import jax
            jax.block_until_ready(out)
            rec["probe_step_s"] = round(time.perf_counter() - t0, 6)
    except Exception as exc:  # diagnostics must never crash the run
        get_logger("observe.costmodel").info(
            "cost capture unavailable for %s program %s: %s",
            where, program, exc)
        tracer = run.tracer
        tracer.event("program_cost_unavailable", cat="cost", where=where,
                     program=program, error=str(exc))
        return None
    run.record_program_cost(where, program, rec)
    run.tracer.event("program_cost", cat="cost", where=where,
                     program=program, **rec)
    return rec


def roofline(flops: Optional[float], bytes_accessed: Optional[float],
             step_s: Optional[float], peak_flops: Optional[float] = None,
             peak_bw: Optional[float] = None,
             host_floor: float = HOST_BOUND_FLOOR) -> dict:
    """One program's roofline placement.

    The ridge point (peak_flops / peak_bw, FLOP per byte) splits the
    roofline: a program whose arithmetic intensity sits above it has a
    compute ceiling, below it a bandwidth ceiling.  The achieved fraction
    of that ceiling (MFU or bw_util) is the verdict's second axis — a
    program under `host_floor` of its own ceiling is not what bounds the
    run, so the verdict is `host-bound` rather than naming a device
    ceiling it never approaches.  Unknown peaks (CPU, unrecognized
    device kinds) yield None utilizations and no verdict — never
    fabricated numbers.
    """
    ai = (flops / bytes_accessed
          if flops and bytes_accessed else None)
    ridge = (peak_flops / peak_bw
             if peak_flops and peak_bw else None)
    mfu = (flops / step_s / peak_flops
           if flops and step_s and peak_flops else None)
    bw_util = (bytes_accessed / step_s / peak_bw
               if bytes_accessed and step_s and peak_bw else None)
    bound = None
    if ai is not None and ridge is not None:
        bound = "compute" if ai >= ridge else "bandwidth"
    util = {"compute": mfu, "bandwidth": bw_util, None: None}[bound]
    verdict = None
    if util is not None:
        verdict = "host-bound" if util < host_floor else f"{bound}-bound"
    return {
        "arithmetic_intensity": round(ai, 3) if ai is not None else None,
        "ridge": round(ridge, 3) if ridge is not None else None,
        "mfu": round(mfu, 5) if mfu is not None else None,
        "hbm_bw_util": round(bw_util, 5) if bw_util is not None else None,
        "bound": bound,
        "verdict": verdict,
    }


def program_summary(costs: dict, times: dict,
                    peak_flops: Optional[float] = None,
                    peak_bw: Optional[float] = None) -> dict:
    """Join cost rows with execution times into the per-program roofline
    table (run_summary's `programs` section and the report's roofline
    view).

    `costs` and `times` are keyed `(where, program)` — costs from
    `capture_program_cost`, times accumulated by the hot loops
    (`RunTelemetry.add_program_time`).  The per-step time each roofline
    uses is the most honest one available: accumulated span walls when
    the live span brackets the execution (`basis='step_wall'`, the
    trainer's synced step spans), else the capture-time probe
    (`basis='dispatch'` paths, whose live spans wall only the async
    dispatch and would overstate utilization wildly).
    """
    out: dict[str, dict] = {}
    for key in sorted(set(costs) | set(times), key=str):
        where, program = key
        cost = costs.get(key, {})
        t = times.get(key, {})
        count = t.get("count", 0)
        basis = t.get("basis")
        span_step_s = (t["seconds"] / count) if count else None
        probe_s = cost.get("probe_step_s")
        if basis == "step_wall" and span_step_s:
            step_s, step_basis = span_step_s, "span_wall"
        elif probe_s:
            step_s, step_basis = probe_s, "probe"
        else:
            step_s, step_basis = span_step_s, basis
        row = {
            "where": where,
            "program": program,
            "flops": cost.get("flops"),
            "bytes_accessed": cost.get("bytes_accessed"),
            "executions": count,
            "span_s": round(t.get("seconds", 0.0), 6),
            "step_s": round(step_s, 6) if step_s else None,
            "step_basis": step_basis,
            **roofline(cost.get("flops"), cost.get("bytes_accessed"),
                       step_s, peak_flops, peak_bw),
        }
        out[f"{where}:{program}"] = row
    return out


def device_peaks() -> tuple:
    """(peak_flops, peak_hbm_bw) of the default device, either None when
    unknown — one lazy import point for the summary/exposition callers."""
    try:
        from mmlspark_tpu.utils.perf import (device_peak_flops,
                                             device_peak_hbm_bw)
        return device_peak_flops(), device_peak_hbm_bw()
    except Exception:
        return None, None
