"""SLO compliance + multi-window burn-rate analytics over the serve timeline.

The serve drills already assert event SEQUENCES (shed ordering, failover
chains); what they cannot answer is the operator question "are we inside
our error budget, and how fast are we spending it?".  This module folds
the run's completion events into that answer:

  * per-lane compliance: each request completion is good (status ok, no
    deadline miss) or bad; compliance = good / total per priority lane
    against MMLSPARK_TPU_SLO_TARGET;
  * multi-window burn rates (Google SRE style): over a trailing window W,
    burn = error_rate_W / (1 - target) — burn 1.0 spends the budget
    exactly at sustainable rate, 14.4 spends 2% of a 30-day budget in an
    hour.  Two windows (fast 5m / slow 1h by default) so a page needs
    BOTH elevated: the fast window confirms it is still happening, the
    slow window confirms it is material;
  * alerts: one record per lane whose fast AND slow burns exceed
    MMLSPARK_TPU_SLO_BURN_ALERT, surfaced under `slo.alerts` in
    run_summary.json and replayed as `slo_alert` records in run.jsonl.

Completion sources, in preference order: fleet-level routing `finish`
events (one per request no matter how many dispatch attempts), falling
back to engine serve `finish` events for bare single-engine runs —
counting both would double every fleet request.
"""

from __future__ import annotations

from typing import Optional

from mmlspark_tpu import config

SLO_TARGET = config.register(
    "MMLSPARK_TPU_SLO_TARGET", 0.99,
    "SLO analytics: target good-request fraction per priority lane "
    "(good = finished ok with no deadline miss); compliance and burn "
    "rates in run_summary's `slo` section are computed against this",
    ptype=float)
SLO_FAST_WINDOW_S = config.register(
    "MMLSPARK_TPU_SLO_FAST_WINDOW_S", 300.0,
    "SLO analytics: fast burn-rate window (seconds) — the 'is it still "
    "happening' half of the multi-window alert condition", ptype=float)
SLO_SLOW_WINDOW_S = config.register(
    "MMLSPARK_TPU_SLO_SLOW_WINDOW_S", 3600.0,
    "SLO analytics: slow burn-rate window (seconds) — the 'is it "
    "material' half of the multi-window alert condition", ptype=float)
SLO_BURN_ALERT = config.register(
    "MMLSPARK_TPU_SLO_BURN_ALERT", 14.4,
    "SLO analytics: burn-rate threshold that BOTH windows must exceed "
    "to emit an alert (14.4 = the SRE-book 2%%-of-monthly-budget-in-an-"
    "hour paging condition)", ptype=float)


def _completions(serve_events: list, routing_events: list) -> list[dict]:
    """Normalise completion samples: [{ts, lane, ok, status}].

    Routing `finish` events are the fleet-level truth (one per request);
    serve `finish` events are the fallback for bare engines.  `ok` folds
    deadline misses in: an answer after its deadline spent budget."""
    out = []
    finishes = [e for e in routing_events if e.get("event") == "finish"]
    source = finishes if finishes else \
        [e for e in serve_events if e.get("event") == "finish"]
    for e in source:
        status = str(e.get("status", "")).lower()
        out.append({
            "ts": float(e.get("ts", 0.0) or 0.0),
            "lane": str(e.get("priority", "default") or "default"),
            "ok": status == "ok" and not e.get("deadline_miss"),
            "status": status,
        })
    return out


def _burn(samples: list[dict], now: float, window_s: float,
          target: float) -> Optional[float]:
    """error_rate over the trailing window, as a multiple of the
    sustainable rate (1 - target).  None when the window saw nothing."""
    recent = [s for s in samples if s["ts"] >= now - window_s]
    if not recent:
        return None
    err = sum(1 for s in recent if not s["ok"]) / len(recent)
    budget = max(1.0 - target, 1e-9)
    return err / budget


def compute_slo(serve_events: list, routing_events: list, *,
                now: float, target: Optional[float] = None) -> dict:
    """The run's SLO rollup (module docstring): per-lane compliance +
    5m/1h burn rates + alerts.  Pure over the event lists — report.py
    and the tests feed it synthetic timelines directly."""
    samples = _completions(serve_events or [], routing_events or [])
    if not samples:
        return {}
    target = float(SLO_TARGET.current()) if target is None else float(target)
    fast_s = float(SLO_FAST_WINDOW_S.current())
    slow_s = float(SLO_SLOW_WINDOW_S.current())
    threshold = float(SLO_BURN_ALERT.current())
    lanes: dict[str, list[dict]] = {}
    for s in samples:
        lanes.setdefault(s["lane"], []).append(s)
    endpoints: dict[str, dict] = {}
    alerts: list[dict] = []
    for lane in sorted(lanes):
        ls = lanes[lane]
        ok = sum(1 for s in ls if s["ok"])
        compliance = ok / len(ls)
        burn_fast = _burn(ls, now, fast_s, target)
        burn_slow = _burn(ls, now, slow_s, target)
        endpoints[lane] = {
            "requests": len(ls),
            "ok": ok,
            "compliance": round(compliance, 6),
            "met": compliance >= target,
            "burn_fast": None if burn_fast is None else round(burn_fast, 4),
            "burn_slow": None if burn_slow is None else round(burn_slow, 4),
        }
        if (burn_fast is not None and burn_fast >= threshold
                and burn_slow is not None and burn_slow >= threshold):
            alerts.append({
                "endpoint": lane,
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "threshold": threshold,
                "window_fast_s": fast_s,
                "window_slow_s": slow_s,
            })
    return {
        "target": target,
        "windows": {"fast_s": fast_s, "slow_s": slow_s},
        "endpoints": endpoints,
        "alerts": alerts,
    }
