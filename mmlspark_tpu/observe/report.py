"""Run-report diagnostic: replay a run.jsonl into the human answer to
"what did that run actually do, and what bounded it".

    python -m mmlspark_tpu.observe.report <run_dir_or_run.jsonl> \
        [--top N] [--format text|json]

`--format json` prints the structured report itself (one JSON object,
every section machine-readable) — the CI-consumption mode; the default
text rendering is for humans.

Sections (each a structured field of `build_report`, rendered by
`render_report` — so tools can consume the dict while humans read the
text):

  * **stage attribution + bottleneck verdict** — the run's thread-seconds
    per pipeline phase, replayed through the SAME PipelineTimings verdict
    logic the live `pipeline_timing()` block uses (observe/spans.py), so
    the offline answer can never drift from the online one;
  * **top-N slowest steps** — per-step/batch/segment spans ranked by
    duration, with their attrs (the "what did step 1234 do" query);
  * **recompiles** — `cat="compile"` events: every new shape class /
    compiled program the run paid for, in order;
  * **roofline** — the per-program cost table (observe/costmodel.py):
    FLOPs, bytes accessed, per-step time, MFU / HBM-bandwidth
    utilization, and the compute/bandwidth/host-bound verdict for every
    compiled program the run captured;
  * **numerics** — the health timeline (observe/numerics.py): probe
    summaries, loss spikes/divergence, non-finite detections;
  * **resilience timeline** — retries, breaker transitions, chaos
    injections, preemption/resume, ordered by timestamp;
  * **counters** — the run's counter deltas.

This module is the CLI whitelisted for raw print() output
(scripts/lint.py): everything else in mmlspark_tpu/ routes through
observe.logging.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterable, Optional

from mmlspark_tpu.observe.spans import PipelineTimings

# span cats ranked in the slowest-steps table: the per-item work units
STEP_CATS = ("step", "batch", "segment", "bucket")


def load_run(path: str) -> list[dict]:
    """Parse a run.jsonl (or a run directory containing one).  Torn tails
    are expected — a preempted run stops mid-line — so undecodable lines
    are skipped, never raised on (the checkpoint-validation posture)."""
    if os.path.isdir(path):
        path = os.path.join(path, "run.jsonl")
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail of a killed run
    return events


def _stage_timings(events: Iterable[dict]) -> Optional[PipelineTimings]:
    """Rebuild the run's PipelineTimings from its stage_timings event so
    the bottleneck verdict is computed by spans.py's own logic."""
    seconds = None
    for ev in events:
        if ev.get("type") == "stage_timings":
            seconds = ev.get("seconds", {})
    if seconds is None:
        return None
    timings = PipelineTimings()
    timings.seconds.update({k: float(v) for k, v in seconds.items()})
    return timings


def build_report(events: list[dict], top: int = 5,
                 source: Optional[str] = None) -> dict:
    """The structured report over a parsed event list.  `source` (the
    run directory, when known) widens the `requests` section to every
    shard under it — a fleet run writes one run.jsonl per process, and
    the waterfalls only stitch across them."""
    spans = [e for e in events if e.get("type") == "span"]
    instants = [e for e in events if e.get("type") == "event"]
    counters = {}
    wall_s = None
    for ev in events:
        if ev.get("type") == "counters":
            counters = ev.get("deltas", {})
        elif ev.get("type") == "run_end":
            wall_s = ev.get("wall_s")
    if wall_s is None and (spans or instants):  # torn run: best effort
        wall_s = max(e["ts"] + e.get("dur", 0.0)
                     for e in spans + instants)

    timings = _stage_timings(events)
    steps = sorted((s for s in spans if s.get("cat") in STEP_CATS),
                   key=lambda s: -s["dur"])
    recompiles = [e for e in instants if e.get("cat") == "compile"]
    resilience = sorted((e for e in instants + spans
                         if e.get("cat") == "resilience"),
                        key=lambda e: e["ts"])
    numerics = sorted(
        (e for e in instants
         if e.get("cat") == "numerics"
         or str(e.get("name", "")).startswith("numerics.")),
        key=lambda e: e["ts"])
    from mmlspark_tpu.observe.trace import aggregate_spans
    return {
        "wall_s": wall_s,
        "events": len(events),
        "stage_seconds": dict(timings.seconds) if timings else {},
        "bottleneck": timings.bottleneck() if timings else None,
        "span_aggregates": aggregate_spans(spans),
        "slowest_steps": steps[:top],
        "recompiles": recompiles,
        "programs": _programs(events),
        "numerics": numerics,
        "resilience": resilience,
        "requests": _requests(events, top, source),
        "slo_alerts": [e for e in events
                       if e.get("type") == "slo_alert"],
        "counters": counters,
    }


def _requests(events: list[dict], top: int,
              source: Optional[str]) -> dict:
    """The distributed-tracing section: top-N slowest request waterfalls
    (observe/assemble.py).  Cross-process runs shard their timelines one
    run.jsonl per process; given the run DIRECTORY we stitch every shard
    under it, torn or missing shards degrading to notes — a crashed
    worker's half-written shard must never sink the report."""
    from mmlspark_tpu.observe.assemble import assemble, assemble_dir
    if source is not None and os.path.isdir(source):
        asm = assemble_dir(source)
    else:
        asm = assemble(events)
    return {
        "total": len(asm["waterfalls"]),
        "orphans": len(asm["orphans"]),
        "degraded": asm["degraded"],
        "slowest": asm["waterfalls"][:max(0, top)],
    }


def _programs(events: list[dict]) -> dict:
    """The per-program roofline table: the sealed `programs` event when
    the run finished cleanly; for a torn run, a degraded table rebuilt
    from the `program_cost` capture events (costs without times)."""
    table = {}
    for ev in events:
        if ev.get("type") == "programs":
            table = ev.get("programs", {})
    if table:
        return table
    for ev in events:
        if ev.get("type") == "event" and ev.get("name") == "program_cost":
            a = ev.get("attrs", {})
            key = f"{a.get('where')}:{a.get('program')}"
            table[key] = {
                "where": a.get("where"), "program": a.get("program"),
                "flops": a.get("flops"),
                "bytes_accessed": a.get("bytes_accessed"),
                "executions": 0, "span_s": 0.0,
                "step_s": a.get("probe_step_s"), "step_basis": "probe",
                "mfu": None, "hbm_bw_util": None, "bound": None,
                "verdict": None,
            }
    return table


def _attrs_str(attrs: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in attrs.items())


def render_report(report: dict) -> str:
    """The human text for a built report."""
    lines = ["== mmlspark_tpu run report =="]
    if report["wall_s"] is not None:
        lines.append(f"wall: {report['wall_s']:.3f}s over "
                     f"{report['events']} events")

    lines.append("")
    lines.append("-- stage attribution (thread-seconds) --")
    if report["stage_seconds"]:
        total = sum(report["stage_seconds"].values()) or 1.0
        for stage, s in sorted(report["stage_seconds"].items(),
                               key=lambda kv: -kv[1]):
            lines.append(f"  {stage:<10} {s:9.4f}s  {100 * s / total:5.1f}%")
        lines.append(f"  bottleneck verdict: {report['bottleneck']}")
    else:
        lines.append("  (no stage timings recorded)")

    lines.append("")
    lines.append(f"-- top {len(report['slowest_steps'])} slowest steps --")
    for s in report["slowest_steps"]:
        lines.append(f"  {s['dur'] * 1e3:9.2f}ms  {s['name']:<16} "
                     f"@{s['ts']:.3f}s  {_attrs_str(s.get('attrs', {}))}")
    if not report["slowest_steps"]:
        lines.append("  (no step/batch/segment spans)")

    lines.append("")
    lines.append(f"-- recompiles ({len(report['recompiles'])}) --")
    for e in report["recompiles"]:
        lines.append(f"  @{e['ts']:.3f}s {e['name']} "
                     f"{_attrs_str(e.get('attrs', {}))}")
    if not report["recompiles"]:
        lines.append("  (none recorded)")

    lines.append("")
    progs = report.get("programs", {})
    lines.append(f"-- roofline: compiled programs ({len(progs)}) --")
    for key in sorted(progs):
        p = progs[key]
        flops = p.get("flops")
        step_s = p.get("step_s")
        parts = [f"  {key}"]
        parts.append(f"    {p.get('executions', 0)} execution(s)"
                     + (f", {step_s * 1e3:.3f} ms/step "
                        f"({p.get('step_basis')})" if step_s else ""))
        if flops:
            parts.append(
                f"    {flops:.3e} FLOPs, "
                + (f"{p['bytes_accessed']:.3e} bytes"
                   if p.get("bytes_accessed") else "bytes n/a")
                + (f", AI {p['arithmetic_intensity']:g}"
                   if p.get("arithmetic_intensity") else ""))
        util = []
        if p.get("mfu") is not None:
            util.append(f"MFU {p['mfu']:.4f}")
        if p.get("hbm_bw_util") is not None:
            util.append(f"HBM bw {p['hbm_bw_util']:.4f}")
        verdict = p.get("verdict")
        parts.append("    " + (", ".join(util) + ", " if util else "")
                     + f"verdict: {verdict if verdict else 'unknown (no device peaks)'}")
        lines.extend(parts)
    if not progs:
        lines.append("  (no program costs captured)")

    lines.append("")
    numerics = report.get("numerics", [])
    lines.append(f"-- numerics health ({len(numerics)}) --")
    for e in numerics:
        lines.append(f"  @{e['ts']:.3f}s {e['name']} "
                     f"{_attrs_str(e.get('attrs', {}))}")
    if not numerics:
        lines.append("  (no probes recorded)")

    lines.append("")
    lines.append(f"-- resilience timeline ({len(report['resilience'])}) --")
    for e in report["resilience"]:
        lines.append(f"  @{e['ts']:.3f}s {e['name']} "
                     f"{_attrs_str(e.get('attrs', {}))}")
    if not report["resilience"]:
        lines.append("  (no retries / preemptions / chaos)")

    req = report.get("requests") or {}
    if req.get("total") or req.get("orphans") or req.get("degraded"):
        lines.append("")
        lines.append(f"-- requests: slowest traces "
                     f"({len(req.get('slowest', []))} of "
                     f"{req.get('total', 0)}, "
                     f"{req.get('orphans', 0)} orphaned) --")
        for w in req.get("slowest", []):
            stages = " ".join(
                f"{name}={dur * 1e3:.2f}ms"
                for name, dur in sorted((w.get("stages") or {}).items(),
                                        key=lambda kv: -kv[1]))
            flags = []
            if w.get("degraded"):
                flags.append("DEGRADED")
            if w.get("tail"):
                flags.append(f"tail:{w['tail']}")
            lines.append(
                f"  {w['trace'][:16]}  {w.get('wall_s', 0) * 1e3:9.2f}ms  "
                f"{w.get('status') or '?':<8} x{w.get('attempts', 1)}  "
                f"{stages}"
                + (("  [" + " ".join(flags) + "]") if flags else ""))
        for note in req.get("degraded", []):
            lines.append(f"  (degraded: {note})")

    alerts = report.get("slo_alerts") or []
    if alerts:
        lines.append("")
        lines.append(f"-- SLO burn alerts ({len(alerts)}) --")
        for a in alerts:
            lines.append(
                f"  {a.get('endpoint')}: burn fast={a.get('burn_fast')} "
                f"slow={a.get('burn_slow')} "
                f"(threshold {a.get('threshold')})")

    if report["counters"]:
        lines.append("")
        lines.append("-- counter deltas --")
        for name in sorted(report["counters"]):
            lines.append(f"  {name:<32} {report['counters'][name]:g}")
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.observe.report",
        description="Replay a run.jsonl into the human run diagnostic.")
    parser.add_argument("run", help="run directory or run.jsonl path")
    parser.add_argument("--top", type=int, default=5,
                        help="slowest steps to list (default 5)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text",
                        help="json prints the structured report dict "
                             "(machine-readable, for CI)")
    args = parser.parse_args(argv)
    events = load_run(args.run)
    if not events:
        print(f"no events in {args.run}")
        return 1
    report = build_report(events, top=args.top, source=args.run)
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True, default=str))
    else:
        print(render_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
