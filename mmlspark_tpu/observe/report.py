"""Run-report diagnostic: replay a run.jsonl into the human answer to
"what did that run actually do, and what bounded it".

    python -m mmlspark_tpu.observe.report <run_dir_or_run.jsonl> [--top N]

Sections (each a structured field of `build_report`, rendered by
`render_report` — so tools can consume the dict while humans read the
text):

  * **stage attribution + bottleneck verdict** — the run's thread-seconds
    per pipeline phase, replayed through the SAME PipelineTimings verdict
    logic the live `pipeline_timing()` block uses (observe/spans.py), so
    the offline answer can never drift from the online one;
  * **top-N slowest steps** — per-step/batch/segment spans ranked by
    duration, with their attrs (the "what did step 1234 do" query);
  * **recompiles** — `cat="compile"` events: every new shape class /
    compiled program the run paid for, in order;
  * **resilience timeline** — retries, breaker transitions, chaos
    injections, preemption/resume, ordered by timestamp;
  * **counters** — the run's counter deltas.

This module is the CLI whitelisted for raw print() output
(scripts/lint.py): everything else in mmlspark_tpu/ routes through
observe.logging.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterable, Optional

from mmlspark_tpu.observe.spans import PipelineTimings

# span cats ranked in the slowest-steps table: the per-item work units
STEP_CATS = ("step", "batch", "segment", "bucket")


def load_run(path: str) -> list[dict]:
    """Parse a run.jsonl (or a run directory containing one).  Torn tails
    are expected — a preempted run stops mid-line — so undecodable lines
    are skipped, never raised on (the checkpoint-validation posture)."""
    if os.path.isdir(path):
        path = os.path.join(path, "run.jsonl")
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail of a killed run
    return events


def _stage_timings(events: Iterable[dict]) -> Optional[PipelineTimings]:
    """Rebuild the run's PipelineTimings from its stage_timings event so
    the bottleneck verdict is computed by spans.py's own logic."""
    seconds = None
    for ev in events:
        if ev.get("type") == "stage_timings":
            seconds = ev.get("seconds", {})
    if seconds is None:
        return None
    timings = PipelineTimings()
    timings.seconds.update({k: float(v) for k, v in seconds.items()})
    return timings


def build_report(events: list[dict], top: int = 5) -> dict:
    """The structured report over a parsed event list."""
    spans = [e for e in events if e.get("type") == "span"]
    instants = [e for e in events if e.get("type") == "event"]
    counters = {}
    wall_s = None
    for ev in events:
        if ev.get("type") == "counters":
            counters = ev.get("deltas", {})
        elif ev.get("type") == "run_end":
            wall_s = ev.get("wall_s")
    if wall_s is None and (spans or instants):  # torn run: best effort
        wall_s = max(e["ts"] + e.get("dur", 0.0)
                     for e in spans + instants)

    timings = _stage_timings(events)
    steps = sorted((s for s in spans if s.get("cat") in STEP_CATS),
                   key=lambda s: -s["dur"])
    recompiles = [e for e in instants if e.get("cat") == "compile"]
    resilience = sorted((e for e in instants + spans
                         if e.get("cat") == "resilience"),
                        key=lambda e: e["ts"])
    from mmlspark_tpu.observe.trace import aggregate_spans
    return {
        "wall_s": wall_s,
        "events": len(events),
        "stage_seconds": dict(timings.seconds) if timings else {},
        "bottleneck": timings.bottleneck() if timings else None,
        "span_aggregates": aggregate_spans(spans),
        "slowest_steps": steps[:top],
        "recompiles": recompiles,
        "resilience": resilience,
        "counters": counters,
    }


def _attrs_str(attrs: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in attrs.items())


def render_report(report: dict) -> str:
    """The human text for a built report."""
    lines = ["== mmlspark_tpu run report =="]
    if report["wall_s"] is not None:
        lines.append(f"wall: {report['wall_s']:.3f}s over "
                     f"{report['events']} events")

    lines.append("")
    lines.append("-- stage attribution (thread-seconds) --")
    if report["stage_seconds"]:
        total = sum(report["stage_seconds"].values()) or 1.0
        for stage, s in sorted(report["stage_seconds"].items(),
                               key=lambda kv: -kv[1]):
            lines.append(f"  {stage:<10} {s:9.4f}s  {100 * s / total:5.1f}%")
        lines.append(f"  bottleneck verdict: {report['bottleneck']}")
    else:
        lines.append("  (no stage timings recorded)")

    lines.append("")
    lines.append(f"-- top {len(report['slowest_steps'])} slowest steps --")
    for s in report["slowest_steps"]:
        lines.append(f"  {s['dur'] * 1e3:9.2f}ms  {s['name']:<16} "
                     f"@{s['ts']:.3f}s  {_attrs_str(s.get('attrs', {}))}")
    if not report["slowest_steps"]:
        lines.append("  (no step/batch/segment spans)")

    lines.append("")
    lines.append(f"-- recompiles ({len(report['recompiles'])}) --")
    for e in report["recompiles"]:
        lines.append(f"  @{e['ts']:.3f}s {e['name']} "
                     f"{_attrs_str(e.get('attrs', {}))}")
    if not report["recompiles"]:
        lines.append("  (none recorded)")

    lines.append("")
    lines.append(f"-- resilience timeline ({len(report['resilience'])}) --")
    for e in report["resilience"]:
        lines.append(f"  @{e['ts']:.3f}s {e['name']} "
                     f"{_attrs_str(e.get('attrs', {}))}")
    if not report["resilience"]:
        lines.append("  (no retries / preemptions / chaos)")

    if report["counters"]:
        lines.append("")
        lines.append("-- counter deltas --")
        for name in sorted(report["counters"]):
            lines.append(f"  {name:<32} {report['counters'][name]:g}")
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.observe.report",
        description="Replay a run.jsonl into the human run diagnostic.")
    parser.add_argument("run", help="run directory or run.jsonl path")
    parser.add_argument("--top", type=int, default=5,
                        help="slowest steps to list (default 5)")
    args = parser.parse_args(argv)
    events = load_run(args.run)
    if not events:
        print(f"no events in {args.run}")
        return 1
    print(render_report(build_report(events, top=args.top)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
