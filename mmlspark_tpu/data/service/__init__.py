"""Disaggregated data service: a multi-process ingestion tier.

BENCH_r05's roofline verdict was host-bound — one host's CPUs cannot
feed the chips — and PR 9's in-process Dataset graph can only scale
threads.  This package moves graph execution off the consumer:
`Dataset.distribute()` serializes the plan (data/graph.py), a
dispatcher (dispatcher.py) cuts the output stream into splits and
drives a fleet of worker processes (worker.py) over length-prefixed
socket frames (transport.py — the package's ONLY socket/subprocess
module, lint-enforced), and the consumer pulls ready elements either
first-come (dynamic sharding) or reassembled byte-identically
(deterministic mode).  See docs/data-service.md for the deployment
modes, the determinism contract, and snapshot/resume.

Knobs (all overridable per-`distribute()` call):

  MMLSPARK_TPU_DATA_SERVICE_WORKERS       fleet size (0 = autoscale,
                                          negative = bypass service)
  MMLSPARK_TPU_DATA_SERVICE_MODE          'process' | 'inproc'
  MMLSPARK_TPU_DATA_SERVICE_SPLIT_ELEMS   elements per split
  MMLSPARK_TPU_DATA_SERVICE_MAX_WORKERS   autoscale ceiling
  MMLSPARK_TPU_DATA_SERVICE_RESPAWNS      worker respawn budget
  MMLSPARK_TPU_DATA_SERVICE_START_TIMEOUT first-data deadline (s)
  MMLSPARK_TPU_DATA_SERVICE_WORKER_LOG    per-worker stderr log dir
  MMLSPARK_TPU_DATA_SERVICE_WORKER_NS     (registered in
                                          parallel/prefetch.py) gauge
                                          namespace inside a worker
"""

from __future__ import annotations

from mmlspark_tpu import config

SERVICE_WORKERS = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_WORKERS", default=2, ptype=int,
    doc="Default worker count for Dataset.distribute(): positive pins "
        "the fleet size, 0 autoscales from one worker on stall evidence "
        "(data/autotune.py), negative bypasses the service entirely "
        "(the graph runs locally in-process).")

SERVICE_MODE = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_MODE", default="process",
    doc="Default worker driver: 'process' spawns real worker processes "
        "streaming over localhost sockets (the throughput tier); "
        "'inproc' pumps the same WorkerCore cooperatively on the "
        "consumer thread — thread-free and deterministic, what drills "
        "and restricted environments use.")

SERVICE_SPLIT_ELEMS = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_SPLIT_ELEMS", default=8, ptype=int,
    doc="Elements per service split (the re-dispatch/recovery unit and "
        "the deterministic-mode reassembly granularity). Larger splits "
        "amortize per-split graph rebuilds; smaller ones bound redone "
        "work after a worker crash.")

SERVICE_MAX_WORKERS = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_MAX_WORKERS", default=4, ptype=int,
    doc="Autoscale ceiling on a session's worker fleet (the Autotuner "
        "widens worker count like a stage depth, never past this).")

SERVICE_RESPAWNS = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_RESPAWNS", default=2, ptype=int,
    doc="How many replacement workers a session may spawn after the "
        "whole fleet has died before giving up with DataServiceError "
        "(single-worker crash recovery re-dispatches to survivors and "
        "does not draw on this budget).")

SERVICE_START_TIMEOUT = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_START_TIMEOUT", default=120.0,
    ptype=float,
    doc="Seconds the consumer will wait for the FIRST element before "
        "declaring the fleet unable to start (worker spawn + import + "
        "connect happens inside this window; once data flows the "
        "deadline no longer applies).")

SERVICE_CHAOS = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_CHAOS", default=None,
    doc="Worker-side fault injection (crash:<elem>|slow:<seconds>), set "
        "by the dispatcher in a spawned worker's environment when a "
        "chaos script targets it — drills and tests only, never by "
        "hand.")

SERVICE_WORKER_LOG = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_WORKER_LOG", default=None,
    doc="Directory for per-worker stderr logs (worker-<k>.log); unset "
        "sends worker stderr to /dev/null. Set when debugging worker "
        "crashes the dispatcher only sees as 'connection lost'.")

from mmlspark_tpu.data.service.dispatcher import (  # noqa: E402
    DataService, DataServiceError, ServiceSession)

__all__ = ["DataService", "DataServiceError", "ServiceSession"]
