"""Consumer-side splice: the service session as a Dataset stage runner.

`Dataset.distribute()` registers a `ServiceConsumer` as the stage named
"service", so everything that already understands stage runners keeps
working unchanged: `DatasetIterator.close()` tears the session (and
its worker fleet) down sink-to-source, and the `Autotuner` reads the
same `stats()` counter surface it reads from a `Prefetcher` — except
here `depth` means *worker processes* (`scale_unit = "workers"`), so
the existing widen-the-bottleneck logic scales the fleet from stall
evidence with no new controller.

The session opens lazily on the first pull, which is what lets a
`snapshot(tag)` op above apply a restore offset (`fast_forward`)
before any split is dispatched — resumed elements are never produced,
not produced-and-dropped.
"""

from __future__ import annotations

from typing import Optional

from mmlspark_tpu.data.service.dispatcher import DataService


class ServiceConsumer:
    """Iterator + tuning surface over one ServiceSession."""

    scale_unit = "workers"   # Autotuner gauge label: depth here = fleet
    depth_floor = 1          # never scale below one worker

    def __init__(self, service: DataService, spec: dict, *,
                 deterministic: bool = True, consumer_index: int = 0,
                 num_consumers: int = 1,
                 split_elems: Optional[int] = None,
                 owns_service: bool = True):
        self._service = service
        self._session = service.session(
            spec, deterministic=deterministic,
            consumer_index=consumer_index, num_consumers=num_consumers,
            split_elems=split_elems)
        self._owns_service = owns_service
        self.tunable = service.autoscale

    # -- the Prefetcher tuning surface (depth == workers) ---------------
    @property
    def depth(self) -> int:
        return self._session.target_workers

    @property
    def max_depth(self) -> int:
        return self._service.max_workers

    def set_depth(self, depth: int) -> int:
        return self._session.scale(depth)

    def stats(self) -> dict:
        return self._session.stats()

    # -- snapshot/resume ------------------------------------------------
    def fast_forward(self, n: int) -> bool:
        return self._session.fast_forward(n)

    # -- iteration ------------------------------------------------------
    def __iter__(self) -> "ServiceConsumer":
        return self

    def __next__(self):
        return self._session.next_element()

    def close(self) -> None:
        self._session.close()
