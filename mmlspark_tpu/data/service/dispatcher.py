"""Dispatcher side of the data service: split ledger + worker fleet.

A `ServiceSession` executes one iteration of one serialized graph for
one consumer.  The output stream is cut into fixed-size *splits* —
contiguous element ranges `[i*S, (i+1)*S)` — and the ledger drives
them through pending → assigned → done, at most one split in flight
per worker.  Because a split's contents are a pure function of
(graph, range) (graph.build_range), any worker can produce any split,
which is what makes both scheduling modes and crash recovery cheap:

  * **dynamic** (first-come): elements surface in arrival order —
    highest throughput, order depends on worker timing;
  * **deterministic**: elements are reassembled in split-index order
    from per-split buffers, so the epoch is byte-identical to local
    execution no matter how many workers raced to produce it.

Exactly-once under crashes: workers tag every element with a
per-(split, attempt) sequence number and the ledger keeps a received
cursor per split.  When a worker dies (socket EOF, process exit, or a
chaos `worker_crash`), its unacked split is re-queued in full and
already-received element prefixes are dropped on redelivery — no row
is duplicated or lost.  Worker health feeds per-worker circuit
breakers (`data.service.w<k>`), re-spawns draw from a bounded budget,
and every lifecycle step lands in run_summary's `data_service`
timeline plus `data.service.*` counters/gauges.

Two worker drivers share `WorkerCore`: `ProcWorker` wraps a spawned
subprocess streaming frames over a non-blocking socket (all raw
socket/subprocess work delegated to transport.py), and
`InprocWorker` pumps the core cooperatively on the consumer thread —
deterministic and thread-free, the mode drills and tier-1 tests use.
The whole session is single-threaded: `selectors` polling from the
consumer's pulls, no background threads at all.
"""

from __future__ import annotations

import selectors
from collections import deque
from typing import Optional

from mmlspark_tpu import config
from mmlspark_tpu.data.service.worker import WorkerCore
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.spans import monotonic
from mmlspark_tpu.observe.telemetry import active_run
from mmlspark_tpu.observe.trace import mint_context, trace_event
from mmlspark_tpu.resilience.breaker import CircuitOpenError, get_breaker
from mmlspark_tpu.resilience.chaos import get_injector


class DataServiceError(RuntimeError):
    """The service cannot make progress (workers exhausted, graph
    failed deterministically, or startup timed out)."""


class DataService:
    """Configuration + worker-id allocator for service sessions.  One
    DataService can back many iterators; each `session()` owns its
    worker set (sharded across consumers by split index)."""

    def __init__(self, *, workers: Optional[int] = None,
                 mode: Optional[str] = None,
                 split_elems: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 respawns: Optional[int] = None):
        w = (int(config.get("MMLSPARK_TPU_DATA_SERVICE_WORKERS"))
             if workers is None else int(workers))
        self.autoscale = w == 0
        self.workers = 1 if self.autoscale else max(1, w)
        self.mode = (mode if mode is not None
                     else str(config.get("MMLSPARK_TPU_DATA_SERVICE_MODE")))
        if self.mode not in ("process", "inproc"):
            raise ValueError(f"unknown service mode {self.mode!r}")
        self.split_elems = max(1, int(
            config.get("MMLSPARK_TPU_DATA_SERVICE_SPLIT_ELEMS")
            if split_elems is None else split_elems))
        self.max_workers = max(self.workers, int(
            config.get("MMLSPARK_TPU_DATA_SERVICE_MAX_WORKERS")
            if max_workers is None else max_workers))
        self.respawns = max(0, int(
            config.get("MMLSPARK_TPU_DATA_SERVICE_RESPAWNS")
            if respawns is None else respawns))
        self._next_worker_id = 0

    def alloc_worker_id(self) -> int:
        wid = self._next_worker_id
        self._next_worker_id += 1
        return wid

    def session(self, spec: dict, **kwargs) -> "ServiceSession":
        return ServiceSession(self, spec, **kwargs)


class _Split:
    __slots__ = ("index", "start", "stop", "state", "worker", "received",
                 "consumed", "n", "attempts")

    def __init__(self, index: int, start: int, stop: int):
        self.index = index
        self.start = start
        self.stop = stop
        self.state = "pending"     # pending -> assigned -> done
        self.worker = None
        self.received = 0          # dedup cursor across attempts
        self.consumed = 0          # handed to the consumer
        self.n: Optional[int] = None
        self.attempts = 0


class InprocWorker:
    """Cooperative in-process worker: same WorkerCore as a subprocess,
    pumped a few elements at a time from the consumer thread.  Chaos
    faults come straight from the active injector."""

    mode = "inproc"

    def __init__(self, worker_id: int, spec: dict):
        self.worker_id = worker_id
        self.core = WorkerCore(spec, sync=True)
        self.alive = True
        self.ready = True
        self.split: Optional[_Split] = None
        self.slow_factor = 1
        self._gen = None

    def assign(self, split: _Split) -> None:
        self.split = split
        self._gen = self.core.run_split(split.start, split.stop)

    def pump(self, session: "ServiceSession", budget: int) -> None:
        if not self.alive or self._gen is None:
            return
        injector = get_injector()
        for _ in range(max(1, budget // max(1, self.slow_factor))):
            if injector is not None:
                for f in injector.data_faults_due(self.worker_id,
                                                  self.core.produced):
                    if f.kind == "worker_slow":
                        self.slow_factor = max(1, int(f.factor))
                    elif f.kind == "worker_crash":
                        self.stop()
                        session._on_dead(self, "chaos worker_crash")
                        return
            split = self.split
            try:
                seq, obj = next(self._gen)
            except StopIteration:
                self.split = None
                self._gen = None
                session._on_split_end(self, split, None,
                                      self.core.last_stats)
                return
            except Exception as e:
                self.stop()
                session._on_error(self, f"{type(e).__name__}: {e}")
                return
            session._on_elem(self, split, seq, obj)
            if self.split is None:
                return

    def stop(self) -> None:
        self.alive = False
        self.ready = False
        if self._gen is not None:
            self._gen.close()
            self._gen = None


class ProcWorker:
    """A spawned worker subprocess and its dispatcher-side socket."""

    mode = "process"

    def __init__(self, worker_id: int, proc):
        self.worker_id = worker_id
        self.proc = proc
        self.conn = None            # attached when its hello arrives
        self.buf = None
        self.produced = 0           # lifetime count relayed at split_end
        self.alive = True
        self.ready = False          # hello seen + graph sent
        self.split: Optional[_Split] = None
        self.trace_wire = None      # session TraceContext wire form

    def attach(self, conn, buf) -> None:
        self.conn = conn
        self.buf = buf

    def _send(self, msg: dict) -> None:
        from mmlspark_tpu.data.service import transport
        self.conn.setblocking(True)
        try:
            transport.send_json(self.conn, msg)
        finally:
            self.conn.setblocking(False)

    def send_graph(self, spec: dict, trace=None) -> None:
        self.trace_wire = trace
        msg = {"t": "graph", "spec": spec, "sync": False}
        if trace is not None:
            msg["trace"] = trace
        self._send(msg)
        self.ready = True

    def assign(self, split: _Split) -> None:
        self.split = split
        msg = {"t": "split", "id": split.index,
               "start": split.start, "stop": split.stop}
        if self.trace_wire is not None:
            # the trace context rides every worker frame: the worker
            # echoes its id on split_end, tying subprocess production
            # back to the session's waterfall
            msg["trace"] = self.trace_wire
        self._send(msg)

    def stop(self) -> None:
        self.alive = False
        self.ready = False
        if self.conn is not None:
            try:
                self._send({"t": "stop"})
            except OSError:
                pass
            try:
                self.conn.close()
            except OSError:
                pass
            self.conn = None
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except Exception:
                self.proc.kill()
                self.proc.wait(timeout=5)


class ServiceSession:
    """One consumer's live stream over the worker fleet (see module
    docstring for the scheduling/recovery model)."""

    MAX_SPLIT_ATTEMPTS = 5

    def __init__(self, service: DataService, spec: dict, *,
                 deterministic: bool = True, consumer_index: int = 0,
                 num_consumers: int = 1,
                 split_elems: Optional[int] = None):
        if not (0 <= consumer_index < num_consumers):
            raise ValueError(
                f"consumer_index {consumer_index} out of range for "
                f"{num_consumers} consumers")
        self.service = service
        self.spec = spec
        self.deterministic = deterministic
        self.consumer_index = consumer_index
        self.num_consumers = num_consumers
        self.split_elems = max(1, int(split_elems if split_elems is not None
                                      else service.split_elems))
        self.target_workers = service.workers
        self.offset = 0             # fast-forward: first element to produce
        self._started = False
        self._closed = False
        self._error: Optional[str] = None
        self._workers: list = []
        self._splits: dict[int, _Split] = {}
        self._redispatch: deque = deque()
        self._next_index = consumer_index
        self._end_index: Optional[int] = None
        self._ready: deque = deque()              # dynamic mode
        self._det_buf: dict[int, deque] = {}      # deterministic mode
        self._cursor = consumer_index
        self._respawns_left = service.respawns
        self._spawned = 0
        self._redispatches = 0
        self._delivered = 0
        self._counters = {"deliveries": 0, "stalls": 0,
                          "stall_s": 0.0, "residency": 0}
        self._run = active_run()
        self.trace = None           # minted at start() when tracing is on
        self._selector = None
        self._server = None
        self._port: Optional[int] = None
        self._deadline: Optional[float] = None

    # -- telemetry ------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        inc_counter(f"data.service.{kind}")
        if self.trace is not None:
            fields.setdefault("trace", self.trace.trace_id)
            fields.setdefault("sampled", self.trace.sampled)
        payload = {"kind": kind, **fields}
        trace_event(f"data.service.{kind}", cat="data", **fields)
        if self._run is not None:
            self._run.record_data_service(payload)

    def _worker_gauges(self, worker, stats: dict) -> None:
        if self._run is None:
            return
        ns = f"data.service.w{worker.worker_id}"
        self._run.gauge(f"{ns}.produced", worker_produced(worker))
        for stage, st in (stats or {}).items():
            for key in ("deliveries", "stalls"):
                if key in st:
                    self._run.gauge(f"{ns}.{stage}.{key}", st[key])

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        # one trace per session: the data tier is its own root span in
        # the fleet waterfall (kind "admit" opens it, "finish" closes it)
        self.trace = mint_context()
        self._deadline = monotonic() + float(
            config.get("MMLSPARK_TPU_DATA_SERVICE_START_TIMEOUT"))
        if self.service.mode == "process":
            from mmlspark_tpu.data.service import transport
            self._selector = selectors.DefaultSelector()
            self._server, self._port = transport.listen()
            self._selector.register(self._server, selectors.EVENT_READ,
                                    None)
        for _ in range(self.target_workers):
            self._spawn()
        self._event("session_start", mode=self.service.mode,
                    workers=self.target_workers,
                    deterministic=self.deterministic,
                    split_elems=self.split_elems, offset=self.offset,
                    consumer=self.consumer_index,
                    consumers=self.num_consumers)
        if self.trace is not None:
            self._event("admit", mode=self.service.mode,
                        workers=self.target_workers)
        self._maybe_dispatch()

    def fast_forward(self, n: int) -> bool:
        """Shift the dispatch origin by `n` elements (snapshot resume).
        Only before the first pull, and only for an unsharded consumer —
        offsets are counted in this consumer's own element stream."""
        if self._started or self.num_consumers != 1 or n <= 0:
            return False
        self.offset += int(n)
        self._event("resume", offset=self.offset)
        return True

    def _chaos_env(self, worker_id: int) -> dict:
        injector = get_injector()
        if injector is None:
            return {}
        parts = []
        for f in injector.data_faults_for(worker_id):
            if f.kind == "worker_crash":
                parts.append(f"crash:{f.at_elem}")
            elif f.kind == "worker_slow":
                parts.append(f"slow:{max(1, int(f.factor)) * 0.001}")
        if not parts:
            return {}
        return {"MMLSPARK_TPU_DATA_SERVICE_CHAOS": ",".join(parts)}

    def _spawn(self) -> None:
        wid = self.service.alloc_worker_id()
        self._spawned += 1
        if self.service.mode == "inproc":
            self._workers.append(InprocWorker(wid, self.spec))
        else:
            from mmlspark_tpu.data.service import transport
            proc = transport.spawn_worker(wid, "127.0.0.1", self._port,
                                          env=self._chaos_env(wid))
            self._workers.append(ProcWorker(wid, proc))

    def _alive(self) -> list:
        return [w for w in self._workers if w.alive]

    def scale(self, n: int) -> int:
        """Resize the fleet toward `n` workers (the Autotuner's lever,
        via ServiceConsumer.set_depth).  Growth spawns immediately;
        shrink retires idle workers and defers busy ones."""
        n = max(1, min(int(n), self.service.max_workers))
        if not self._started:
            self.target_workers = n
            return n
        old = self.target_workers
        if n != old:
            self.target_workers = n
            self._event("scale", workers_from=old, workers_to=n)
        self._reconcile()
        return self.target_workers

    def _reconcile(self) -> None:
        alive = self._alive()
        while len(alive) < self.target_workers:
            self._spawn()
            alive = self._alive()
        extra = len(alive) - self.target_workers
        for w in alive:
            if extra <= 0:
                break
            if w.split is None:
                w.stop()
                extra -= 1

    # -- ledger ---------------------------------------------------------
    def _split_for(self, index: int) -> _Split:
        s = self._splits.get(index)
        if s is None:
            base = self.offset + index * self.split_elems
            s = _Split(index, base, base + self.split_elems)
            self._splits[index] = s
        return s

    def _open_count(self) -> int:
        return sum(1 for s in self._splits.values()
                   if s.n is None or s.consumed < s.n)

    def _next_split(self) -> Optional[_Split]:
        while self._redispatch:
            s = self._redispatch.popleft()
            if s.state == "pending":
                return s
        window = max(4, 2 * max(1, len(self._alive())))
        if self._open_count() >= window:
            return None
        index = self._next_index
        if self._end_index is not None and index > self._end_index:
            return None
        self._next_index += self.num_consumers
        return self._split_for(index)

    def _maybe_dispatch(self) -> None:
        self._reconcile()
        for w in self._workers:
            if not (w.alive and w.ready) or w.split is not None:
                continue
            breaker = get_breaker(f"data.service.w{w.worker_id}")
            try:
                breaker.allow()
            except CircuitOpenError:
                continue
            s = self._next_split()
            if s is None:
                return
            s.state = "assigned"
            s.worker = w
            s.attempts += 1
            try:
                w.assign(s)
            except OSError:
                self._on_dead(w, "assign failed")
                continue
            self._event("dispatch", split=s.index, worker=w.worker_id,
                        start=s.start, stop=s.stop, attempt=s.attempts)

    # -- worker callbacks ----------------------------------------------
    def _on_elem(self, worker, split, seq: int, obj) -> None:
        if isinstance(split, int):
            split = self._splits.get(split)
        if split is None:
            return
        if seq < split.received:
            inc_counter("data.service.dup_dropped")
            return
        if seq > split.received:
            self._on_dead(worker, f"sequence gap on split {split.index}")
            return
        split.received += 1
        if self.deterministic:
            self._det_buf.setdefault(split.index, deque()).append(obj)
        else:
            self._ready.append((split.index, obj))

    def _on_split_end(self, worker, split, n: Optional[int],
                      stats) -> None:
        if isinstance(split, int):
            split = self._splits.get(split)
        if split is None:
            return
        if n is None:
            n = split.received
        if worker.split is split:
            worker.split = None
        split.state = "done"
        split.n = int(n)
        get_breaker(f"data.service.w{worker.worker_id}").record_success()
        self._worker_gauges(worker, stats)
        self._event("split_end", split=split.index,
                    worker=worker.worker_id, n=split.n)
        if split.n < split.stop - split.start:
            end = split.index
            if self._end_index is None or end < self._end_index:
                self._end_index = end
        self._maybe_dispatch()

    def _on_error(self, worker, msg: str) -> None:
        # deterministic graph failure: re-dispatch would just repeat it
        self._error = msg
        self._event("worker_error", worker=worker.worker_id, error=msg)

    def _on_dead(self, worker, reason: str) -> None:
        if not worker.alive and worker.split is None:
            return
        worker.alive = False
        worker.ready = False
        s = worker.split
        worker.split = None
        get_breaker(f"data.service.w{worker.worker_id}").record_failure()
        self._event("worker_dead", worker=worker.worker_id, reason=reason,
                    split=None if s is None else s.index)
        if s is not None and s.state == "assigned":
            s.state = "pending"
            s.worker = None
            if s.attempts >= self.MAX_SPLIT_ATTEMPTS:
                self._error = (f"split {s.index} failed "
                               f"{s.attempts} times (last: {reason})")
                return
            self._redispatches += 1
            self._redispatch.append(s)
            self._event("redispatch", split=s.index, received=s.received)
        if not self._alive():
            if self._respawns_left > 0:
                self._respawns_left -= 1
                self._event("respawn", remaining=self._respawns_left)
                self._spawn()
            else:
                self._error = self._error or (
                    f"all workers dead (last: {reason}), "
                    "respawn budget exhausted")
        self._maybe_dispatch()

    # -- pumping --------------------------------------------------------
    def _pump(self, timeout_s: float) -> None:
        if self.service.mode == "inproc":
            for w in list(self._workers):
                if w.alive and w.split is not None:
                    w.pump(self, budget=4)
            return
        self._pump_sockets(timeout_s)

    def _pump_sockets(self, timeout_s: float) -> None:
        from mmlspark_tpu.data.service import transport
        for key, _ in self._selector.select(timeout_s):
            if key.fileobj is self._server:
                conn = transport.accept(self._server, 0.0)
                if conn is not None:
                    buf = transport.FrameBuffer()
                    self._selector.register(conn, selectors.EVENT_READ,
                                            [None, buf])
                continue
            conn, slot = key.fileobj, key.data
            data = transport.recv_ready(conn)
            dead = data is None
            if data:
                slot[1].feed(data)
                try:
                    for frame in slot[1].frames():
                        self._on_frame(conn, slot, frame)
                except transport.TransportError:
                    dead = True
            if dead:
                self._selector.unregister(conn)
                try:
                    conn.close()
                except OSError:
                    pass
                w = slot[0]
                if w is not None and w.alive:
                    w.conn = None
                    self._on_dead(w, "connection lost")
        # a worker that died before (or without) connecting never
        # produces a socket event — poll the processes directly
        for w in self._workers:
            if (w.alive and w.conn is None
                    and w.proc.poll() is not None):
                self._on_dead(w, f"exited {w.proc.returncode} "
                                 "before connecting")

    def _on_frame(self, conn, slot, frame) -> None:
        worker = slot[0]
        if frame[0] == "elem":
            if worker is not None:
                self._on_elem(worker, frame[1], frame[2], frame[3])
            return
        msg = frame[1]
        kind = msg.get("t")
        if kind == "hello":
            wid = int(msg.get("worker", -1))
            for w in self._workers:
                if w.worker_id == wid and w.conn is None and w.alive:
                    w.attach(conn, slot[1])
                    slot[0] = w
                    w.send_graph(self.spec,
                                 None if self.trace is None
                                 else self.trace.to_wire())
                    self._maybe_dispatch()
                    return
            return
        if worker is None:
            return
        if kind == "split_end":
            worker.produced = int(msg.get("produced", 0))
            self._on_split_end(worker, int(msg["id"]), int(msg["n"]),
                               msg.get("stats") or {})
        elif kind == "err":
            self._on_error(worker, str(msg.get("msg", "worker error")))

    # -- consuming ------------------------------------------------------
    def _buffered(self) -> int:
        if self.deterministic:
            return sum(len(d) for d in self._det_buf.values())
        return len(self._ready)

    def _det_pop(self):
        while True:
            s = self._splits.get(self._cursor)
            if s is None:
                return _PENDING
            if s.n is not None and s.consumed >= s.n:
                # split fully consumed (possibly empty): advance cursor
                self._det_buf.pop(self._cursor, None)
                self._cursor += self.num_consumers
                self._maybe_dispatch()
                continue
            buf = self._det_buf.get(self._cursor)
            if not buf:
                return _PENDING
            obj = buf.popleft()
            s.consumed += 1
            return obj

    def _dyn_pop(self):
        if not self._ready:
            return _PENDING
        index, obj = self._ready.popleft()
        s = self._splits.get(index)
        if s is not None:
            s.consumed += 1
        self._maybe_dispatch()
        return obj

    def _finished(self) -> bool:
        if self._end_index is None:
            return False
        if self.deterministic:
            return self._cursor > self._end_index
        if self._ready:
            return False
        # splits past the end produce nothing by construction; every
        # split at or below it must be done and fully drained
        return all(s.n is not None and s.consumed >= s.n
                   for s in self._splits.values()
                   if s.index <= self._end_index)

    def next_element(self):
        if self._closed:
            raise StopIteration
        self.start()
        pop = self._det_pop if self.deterministic else self._dyn_pop
        stalled = False
        t0 = 0.0
        while True:
            if self._error is not None:
                raise DataServiceError(self._error)
            obj = pop()
            if obj is not _PENDING:
                self._counters["deliveries"] += 1
                self._counters["residency"] += self._buffered()
                self._delivered += 1
                return obj
            if self._finished():
                raise StopIteration
            if not stalled:
                stalled = True
                t0 = monotonic()
                self._counters["stalls"] += 1
            if not self._alive() and self._error is None:
                # _on_dead respawns or sets the error when the fleet
                # empties; reaching here without either is a stuck state
                self._error = "no live workers and nothing buffered"
                continue
            self._pump(0.05)
            if stalled:
                self._counters["stall_s"] += monotonic() - t0
                t0 = monotonic()
            if (self._deadline is not None and self._delivered == 0
                    and not self._buffered()
                    and monotonic() > self._deadline):
                raise DataServiceError(
                    "no worker produced data before "
                    "MMLSPARK_TPU_DATA_SERVICE_START_TIMEOUT")

    # -- stats / shutdown ----------------------------------------------
    def stats(self) -> dict:
        c = dict(self._counters)
        c["stall_s"] = round(c["stall_s"], 6)
        return c

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for w in self._workers:
            try:
                w.stop()
            except Exception:
                pass
        if self._selector is not None:
            try:
                self._selector.close()
            except Exception:
                pass
            self._selector = None
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
            self._server = None
        if self._started:
            self._event("session_end", delivered=self._delivered,
                        splits=sum(1 for s in self._splits.values()
                                   if s.state == "done"),
                        workers_spawned=self._spawned,
                        redispatches=self._redispatches)
            if self.trace is not None:
                self._event("finish",
                            status="error" if self._error else "ok",
                            delivered=self._delivered)


_PENDING = object()


def worker_produced(worker) -> int:
    core = getattr(worker, "core", None)  # inproc: read the core directly
    return (core.produced if core is not None
            else getattr(worker, "produced", 0))
