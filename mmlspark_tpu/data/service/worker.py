"""Service worker: execute serialized graph splits, stream elements.

`WorkerCore` is the mode-agnostic execution engine — given a graph
spec it builds each assigned split with `graph.build_range` and yields
`(seq, element)` pairs.  Two drivers wrap it:

  * the inproc driver (dispatcher.py `InprocWorker`) pumps the core
    cooperatively on the consumer thread — deterministic, thread-free,
    works everywhere; what drills and tier-1 tests use;
  * this module's `main()` is the process driver: spawned by
    `transport.spawn_worker`, it connects back to the dispatcher,
    handshakes, then loops frames — `graph` installs the plan, `split`
    streams its elements back (`elem` frames with per-attempt sequence
    numbers) followed by `split_end` carrying the element count and the
    split's per-stage `Prefetcher.stats()` counters, which the
    dispatcher republishes as `data.service.w<k>.*` gauges.

Fault injection for chaos drills rides the environment
(`MMLSPARK_TPU_DATA_SERVICE_CHAOS=crash:<n>|slow:<seconds>`): crash
hard-exits after `n` produced elements (the unacked-split re-dispatch
path), slow throttles each element (the autoscaler/stall path).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

from mmlspark_tpu.data import graph


class WorkerChaos:
    """Parsed per-worker fault plan (env-carried for process workers,
    injector-fed for inproc ones)."""

    __slots__ = ("crash_at", "slow_s")

    def __init__(self, crash_at: Optional[int] = None,
                 slow_s: float = 0.0):
        self.crash_at = crash_at
        self.slow_s = slow_s

    @staticmethod
    def from_env(value: str) -> "WorkerChaos":
        chaos = WorkerChaos()
        for part in value.split(","):
            part = part.strip()
            if part.startswith("crash:"):
                chaos.crash_at = int(part.split(":", 1)[1])
            elif part.startswith("slow:"):
                chaos.slow_s = float(part.split(":", 1)[1])
        return chaos


class WorkerCore:
    """Executes splits of one graph; counts total produced elements so
    chaos anchors ("crash at element k") are deterministic."""

    def __init__(self, spec: dict, *, sync: bool = False):
        self.spec = spec
        self.sync = sync
        self.produced = 0
        self.last_stats: dict[str, dict] = {}

    def run_split(self, start: int, stop: int) -> Iterator[tuple]:
        ds = graph.build_range(self.spec, start, stop, sync=self.sync)
        seq = 0
        with ds.iterator(autotune=False) as it:
            for obj in it:
                yield seq, obj
                seq += 1
                self.produced += 1
            self.last_stats = {s.name: dict(s.runner.stats())
                               for s in it.stages
                               if hasattr(s.runner, "stats")}


def _recv_json(sock, buf) -> dict:
    from mmlspark_tpu.data.service import transport
    while True:
        for frame in buf.frames():
            if frame[0] == "json":
                return frame[1]
        data = sock.recv(1 << 16)
        if not data:
            raise transport.TransportError("dispatcher closed connection")
        buf.feed(data)


def _serve(sock, worker_id: int, chaos: WorkerChaos) -> None:
    from mmlspark_tpu.data.service import transport
    from mmlspark_tpu.resilience.clock import get_clock
    buf = transport.FrameBuffer()
    transport.send_json(sock, {"t": "hello", "worker": worker_id})
    core: Optional[WorkerCore] = None
    trace_id: Optional[str] = None   # dispatcher's session trace context,
    #   carried on graph/split frames; echoed on split_end/err so the
    #   subprocess's production joins the session's fleet waterfall
    while True:
        msg = _recv_json(sock, buf)
        kind = msg.get("t")
        ctx = msg.get("trace")
        if isinstance(ctx, dict) and isinstance(ctx.get("id"), str):
            trace_id = ctx["id"]
        if kind == "stop":
            return
        if kind == "graph":
            core = WorkerCore(msg["spec"], sync=bool(msg.get("sync")))
            continue
        if kind != "split":
            raise transport.TransportError(f"unexpected message {kind!r}")
        if core is None:
            raise transport.TransportError("split before graph")
        split_id = int(msg["id"])
        try:
            n = 0
            for seq, obj in core.run_split(int(msg["start"]),
                                           int(msg["stop"])):
                if chaos.slow_s > 0:
                    get_clock().sleep(chaos.slow_s)
                if (chaos.crash_at is not None
                        and core.produced > chaos.crash_at):
                    os._exit(17)  # chaos worker_crash: die unacked
                transport.send_elem(sock, split_id, seq, obj)
                n += 1
            end = {"t": "split_end", "id": split_id, "n": n,
                   "produced": core.produced, "stats": core.last_stats}
            if trace_id is not None:
                end["trace"] = trace_id
            transport.send_json(sock, end)
        except Exception as e:  # deterministic graph errors: report, die
            err = {"t": "err", "id": split_id,
                   "msg": f"{type(e).__name__}: {e}"}
            if trace_id is not None:
                err["trace"] = trace_id
            transport.send_json(sock, err)
            return


def main(argv: Optional[list] = None) -> int:
    import argparse

    from mmlspark_tpu.data.service import transport
    from mmlspark_tpu.resilience.retry import RetryPolicy

    parser = argparse.ArgumentParser(prog="mmlspark_tpu-data-worker")
    parser.add_argument("--connect", required=True,
                        help="dispatcher host:port")
    parser.add_argument("--id", type=int, required=True)
    args = parser.parse_args(argv)
    host, port = args.connect.rsplit(":", 1)
    from mmlspark_tpu import config
    chaos = WorkerChaos.from_env(
        config.get("MMLSPARK_TPU_DATA_SERVICE_CHAOS") or "")
    policy = RetryPolicy.from_config(name="data.service.connect")
    sock = policy.call(lambda: transport.connect(host, int(port)))
    try:
        _serve(sock, args.id, chaos)
    except (transport.TransportError, OSError):
        return 1
    finally:
        try:
            sock.close()
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
