"""The data service's ONLY transport module: sockets + worker spawn.

Everything that touches a raw `socket` or `subprocess` in this package
lives here (lint-enforced, mirroring `serve/lifecycle.py` for threads
and `data/executor.py` for pools), so the wire format and process
lifecycle have exactly one implementation to audit.

Wire format — length-prefixed frames on a localhost TCP stream:

    [4B big-endian length][1B type][payload]

Type ``J``: a JSON control message `{"t": kind, ...}` — handshake
(`hello`), plan delivery (`graph`), work assignment (`split`),
completion (`split_end` with element count + stage stats), worker-side
failure (`err`), shutdown (`stop`).  Type ``E``: one ready element —
`[4B split][4B seq]` + pickled payload, the hot path.  Sequence
numbers are per-(split, attempt), which is what lets the dispatcher
deduplicate redelivered elements after a crash re-dispatch.
Type ``K``: one KV-cache page for the serving tier's prefill->decode
handoff — `[4B request id][4B chunk index][4B byte length][4B crc32]`
+ raw page bytes.  The crc is validated at parse time: a bit-flipped
page surfaces as a typed `TransportError` (carrying `request_id` /
`page_index`) instead of a silently corrupt cache splice, and the bad
frame is consumed first so the stream stays parseable — one torn page
fails one transfer, not the whole link.

Reads on the dispatcher side are non-blocking (`recv_ready` +
`FrameBuffer`) so one consumer thread can pump every worker; writes
are small control frames sent blocking.  Workers use plain blocking
sockets.  `connect` retries under the shared `RetryPolicy` and records
per-worker circuit-breaker outcomes at the call site.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import zlib
from typing import Iterator, Optional

from mmlspark_tpu.observe.spans import monotonic

_HDR = struct.Struct(">IB")
_ELEM = struct.Struct(">II")
_PAGE = struct.Struct(">IIII")  # request id, chunk index, byte len, crc32
_TYPE_JSON = 0x4A   # 'J'
_TYPE_ELEM = 0x45   # 'E'
_TYPE_PAGE = 0x4B   # 'K'
_MAX_FRAME = 1 << 31


class TransportError(ConnectionError):
    """Framing/peer failure on a service connection (retryable class:
    subclasses ConnectionError so `default_classify` retries it).
    Page-integrity failures set `request_id`/`page_index` so the caller
    can fail ONE transfer instead of the whole link."""

    def __init__(self, message: str, *, request_id: Optional[int] = None,
                 page_index: Optional[int] = None):
        super().__init__(message)
        self.request_id = request_id
        self.page_index = page_index


def encode_json(msg: dict) -> bytes:
    import json
    payload = json.dumps(msg, sort_keys=True).encode("utf-8")
    return _HDR.pack(len(payload) + 1, _TYPE_JSON) + payload


def send_json(sock: socket.socket, msg: dict) -> None:
    sock.sendall(encode_json(msg))


def send_elem(sock: socket.socket, split: int, seq: int, obj) -> None:
    payload = _ELEM.pack(split, seq) + pickle.dumps(
        obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(payload) + 1, _TYPE_ELEM) + payload)


def encode_page(request_id: int, page_index: int, data: bytes) -> bytes:
    """One KV page frame: the (request id, chunk index, byte length,
    crc32) header the handoff protocol acks against, then the raw page.
    Encoding is split from sending so the serving tier's single-threaded
    pump can queue frames on a non-blocking socket."""
    payload = _PAGE.pack(request_id, page_index, len(data),
                         zlib.crc32(data)) + data
    return _HDR.pack(len(payload) + 1, _TYPE_PAGE) + payload


def send_page(sock: socket.socket, request_id: int, page_index: int,
              data: bytes) -> None:
    sock.sendall(encode_page(request_id, page_index, data))


class FrameBuffer:
    """Incremental frame parser: `feed` raw bytes, iterate `frames()`.
    Frames come out as ("json", dict), ("elem", split, seq, obj), or
    ("page", request_id, page_index, data) — page frames crc-validated
    at parse time (a failed page raises `TransportError` AFTER consuming
    the frame, so iteration can resume on the next frame)."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> None:
        self._buf.extend(data)

    def pending(self) -> int:
        return len(self._buf)

    def frames(self) -> Iterator[tuple]:
        import json
        while True:
            if len(self._buf) < _HDR.size:
                return
            length, ftype = _HDR.unpack_from(self._buf)
            if not (1 <= length <= _MAX_FRAME):
                raise TransportError(f"bad frame length {length}")
            end = _HDR.size + length - 1
            if len(self._buf) < end:
                return
            payload = bytes(self._buf[_HDR.size:end])
            del self._buf[:end]
            if ftype == _TYPE_JSON:
                yield ("json", json.loads(payload.decode("utf-8")))
            elif ftype == _TYPE_ELEM:
                split, seq = _ELEM.unpack_from(payload)
                yield ("elem", split, seq,
                       pickle.loads(payload[_ELEM.size:]))
            elif ftype == _TYPE_PAGE:
                yield self._page(payload)
            else:
                raise TransportError(f"unknown frame type {ftype:#x}")

    @staticmethod
    def _page(payload: bytes) -> tuple:
        if len(payload) < _PAGE.size:
            raise TransportError(
                f"truncated page header ({len(payload)}B)")
        rid, idx, blen, crc = _PAGE.unpack_from(payload)
        data = payload[_PAGE.size:]
        if len(data) != blen:
            raise TransportError(
                f"torn page {idx} for request {rid}: header says {blen}B, "
                f"frame carries {len(data)}B",
                request_id=rid, page_index=idx)
        if zlib.crc32(data) != crc:
            raise TransportError(
                f"page {idx} for request {rid} failed crc32",
                request_id=rid, page_index=idx)
        return ("page", rid, idx, data)


def read_frame(sock: socket.socket, buf: FrameBuffer,
               timeout_s: float) -> tuple:
    """Blocking read of exactly ONE frame with a bounded wall deadline.
    A stalled peer surfaces as `TransportError` ('stalled') instead of a
    hang, and a peer that closes mid-frame as `TransportError` ('torn')
    instead of a silent short read — the per-page timeout the KV-handoff
    splice path relies on.  Bytes past the first frame stay in `buf`."""
    deadline = monotonic() + max(1e-3, float(timeout_s))
    while True:
        try:
            for frame in buf.frames():
                return frame
        except TransportError:
            raise
        remaining = deadline - monotonic()
        if remaining <= 0:
            raise TransportError(
                f"frame read stalled: no complete frame within "
                f"{timeout_s:.3f}s ({buf.pending()}B buffered)")
        sock.settimeout(remaining)
        try:
            data = sock.recv(1 << 16)
        except socket.timeout:
            continue
        except OSError as e:
            raise TransportError(f"peer failed mid-frame: {e}") from e
        if not data:
            raise TransportError(
                f"torn frame: peer closed with {buf.pending()}B of an "
                f"incomplete frame buffered")
        buf.feed(data)


def listen(host: str = "127.0.0.1") -> tuple[socket.socket, int]:
    """Bind an ephemeral dispatcher port; returns (server_sock, port)."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, 0))
    srv.listen(64)
    return srv, srv.getsockname()[1]


def accept(server: socket.socket,
           timeout_s: float) -> Optional[socket.socket]:
    """Accept one worker connection (None on timeout).  Accepted conns
    come back non-blocking with NODELAY — dispatcher pump sockets."""
    server.settimeout(timeout_s)
    try:
        conn, _ = server.accept()
    except (socket.timeout, BlockingIOError, InterruptedError):
        return None
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    conn.setblocking(False)
    return conn


def connect(host: str, port: int, timeout_s: float = 10.0) -> socket.socket:
    """Worker-side blocking connect (one attempt; callers wrap in the
    shared RetryPolicy)."""
    try:
        sock = socket.create_connection((host, port), timeout=timeout_s)
    except OSError as e:
        raise TransportError(f"connect {host}:{port} failed: {e}") from e
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    return sock


def recv_ready(sock: socket.socket) -> Optional[bytes]:
    """Drain whatever is available on a non-blocking socket.  Returns
    b"" when nothing is pending, bytes when data arrived, None when the
    peer closed or reset (the caller marks the worker dead)."""
    chunks = []
    while True:
        try:
            data = sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            break
        except OSError:
            return None
        if not data:
            return None if not chunks else b"".join(chunks)
        chunks.append(data)
        if len(data) < (1 << 16):
            break
    return b"".join(chunks)


def spawn_worker(worker_id: int, host: str, port: int, *,
                 env: Optional[dict] = None) -> subprocess.Popen:
    """Launch one service worker process that connects back to the
    dispatcher.  Workers are always pinned to CPU JAX (they decode and
    shape host data; they must never claim an accelerator)."""
    from mmlspark_tpu import config
    wenv = dict(os.environ)
    wenv["JAX_PLATFORMS"] = "cpu"
    # gauges emitted inside the worker get a per-worker namespace so N
    # workers reporting into one metrics backend never collide
    wenv["MMLSPARK_TPU_DATA_SERVICE_WORKER_NS"] = \
        f"data.service.w{worker_id}"
    wenv.update(env or {})
    log_dir = str(config.get("MMLSPARK_TPU_DATA_SERVICE_WORKER_LOG")
                  or "")
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        stderr = open(os.path.join(log_dir, f"worker-{worker_id}.log"),
                      "ab")
    else:
        stderr = subprocess.DEVNULL
    return subprocess.Popen(
        [sys.executable, "-m", "mmlspark_tpu.data.service.worker",
         "--connect", f"{host}:{port}", "--id", str(worker_id)],
        stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
        stderr=stderr, env=wenv)
