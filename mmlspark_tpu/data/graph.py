"""Versioned, round-trip-exact serialization of Dataset plans.

The disaggregated data service (data/service/) ships a pipeline to
worker processes as data, not code: every `Dataset` op records a
declarative `_spec` node, and this module turns that chain into a
versioned JSON document (`to_spec`/`dumps`) and back into an
executable `Dataset` (`from_spec`/`loads`).  Round-trips are exact:
`dumps(loads(dumps(ds)))` is byte-identical, and executing the rebuilt
plan yields the same element sequence as the original — that is the
determinism contract the service's byte-identical mode rests on.

Functions cross the process boundary by REFERENCE, never by pickled
code: a module-level function serializes as its import path
(`{"kind": "import", module, qualname}`), verified resolvable at
serialize time so failures surface at `distribute()` on the consumer,
not mid-epoch in a worker.  Callables that aren't importable (closures
built at runtime) can be registered under a stable name at module
import time with `register_fn`; lambdas and unregistered closures are
rejected with `GraphSerializationError`.  `from_table` sources hold a
live in-memory table and never serialize.

`build_range` is the worker-side entry point: it builds the plan
restricted to output elements `[start, stop)` — a *split*.  Index-
preserving ops (1:1 `map`, `prefetch`, `snapshot`, and `batch` via
index arithmetic) are pushed above the skip/take barrier so upstream
work for other splits is never performed; barrier ops (`shuffle`,
`interleave`, `map(on_error="skip")`, sources) replay their seeded
stream below it, which keeps split contents a pure function of
(graph, range) — the property crash re-dispatch relies on.
"""

from __future__ import annotations

import importlib
import json
from typing import Any, Callable, Optional

from mmlspark_tpu.data.dataset import Dataset

GRAPH_VERSION = 1


class GraphSerializationError(ValueError):
    """A Dataset plan (or one of its functions) cannot be expressed in
    the versioned graph spec."""


_REGISTRY: dict[str, Callable] = {}


def register_fn(name: str, fn: Optional[Callable] = None):
    """Register a callable under a stable name for graph references.
    Call at module import time (workers re-register by importing the
    recorded module).  Usable directly or as a decorator."""
    def apply(f: Callable) -> Callable:
        _REGISTRY[name] = f
        return f
    return apply(fn) if fn is not None else apply


def _import_qualname(module: str, qualname: str):
    obj: Any = importlib.import_module(module)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _fn_ref(fn: Callable, op: str) -> dict:
    """Serialize a callable as a resolvable reference, verifying at
    serialize time that the reference round-trips to the same object."""
    for name, f in _REGISTRY.items():
        if f is fn:
            return {"kind": "registered", "name": name,
                    "module": getattr(fn, "__module__", "") or ""}
    mod = getattr(fn, "__module__", None)
    qn = getattr(fn, "__qualname__", None)
    if mod and qn and "<" not in qn:
        try:
            resolved = _import_qualname(mod, qn)
        except Exception:
            resolved = None
        if resolved is fn:
            return {"kind": "import", "module": mod, "qualname": qn}
    raise GraphSerializationError(
        f"{op}: callable {fn!r} is not serializable — it must be a "
        "module-level function (importable as module:qualname) or "
        "registered via data.graph.register_fn at import time; lambdas "
        "and runtime closures cannot cross the service boundary")


def _resolve_fn(ref: dict) -> Callable:
    if ref.get("kind") == "registered":
        name = ref["name"]
        if name not in _REGISTRY and ref.get("module"):
            importlib.import_module(ref["module"])  # triggers register_fn
        if name not in _REGISTRY:
            raise GraphSerializationError(
                f"registered fn {name!r} not found (module "
                f"{ref.get('module')!r} did not register it)")
        return _REGISTRY[name]
    if ref.get("kind") == "import":
        return _import_qualname(ref["module"], ref["qualname"])
    raise GraphSerializationError(f"unknown fn ref {ref!r}")


def _json_check(op: str, params: dict) -> dict:
    try:
        json.dumps(params)
    except (TypeError, ValueError) as e:
        raise GraphSerializationError(
            f"{op}: params are not JSON-serializable ({e})") from None
    return params


def _node_spec(ds: Dataset) -> dict:
    if ds._spec is None:
        raise GraphSerializationError(
            f"dataset {ds._name!r} has no serializable plan (from_table "
            "and distribute nodes hold live process state)")
    op, params, parent = ds._spec
    p = dict(params)
    if op == "map":
        p["fn"] = _fn_ref(p["fn"], "map")
    elif op == "interleave":
        p["sub_fn"] = _fn_ref(p["sub_fn"], "interleave")
    elif op == "iterable":
        items = p["items"]
        if callable(items):
            p["items"] = {"fn": _fn_ref(items, "from_iterable")}
        else:
            p["items"] = _json_check("from_iterable",
                                     {"items": list(items)})["items"]
    node = {"op": op, "params": _json_check(op, p)}
    if parent is not None:
        node["parent"] = _node_spec(parent)
    return node


def to_spec(ds: Dataset) -> dict:
    """Serialize a Dataset plan to a versioned spec dict."""
    return {"version": GRAPH_VERSION, "root": _node_spec(ds)}


def dumps(ds: Dataset) -> str:
    """`to_spec` as canonical JSON (sorted keys — byte-stable)."""
    return json.dumps(to_spec(ds), sort_keys=True, separators=(",", ":"))


def _check_version(spec: dict) -> None:
    v = spec.get("version")
    if v != GRAPH_VERSION:
        raise GraphSerializationError(
            f"graph spec version {v!r} not supported "
            f"(this build speaks version {GRAPH_VERSION})")


def _stage_knobs(params: dict, sync: bool) -> dict:
    # sync=True forces stages inline (depth -1): inproc workers stay
    # thread-free so drills are deterministic on a virtual clock
    return {"depth": -1 if sync else params.get("depth")}


def _build_node(node: Optional[dict], *, sync: bool = False) -> Dataset:
    if node is None:
        raise GraphSerializationError("graph node chain has no source")
    op, p = node["op"], node["params"]
    if op == "iterable":
        items = p["items"]
        src = _resolve_fn(items["fn"]) if isinstance(items, dict) else items
        return Dataset.from_iterable(src, name=p.get("name", "iterable"))
    if op == "files":
        return Dataset.from_files(
            p["path"], recursive=p["recursive"],
            sample_ratio=p["sample_ratio"], inspect_zip=p["inspect_zip"],
            pattern=p["pattern"], seed=p["seed"],
            name=p.get("name", "files"))
    return _apply_node(node, _build_node(node.get("parent"), sync=sync),
                       sync=sync)


def _apply_node(node: dict, parent: Dataset, *, sync: bool) -> Dataset:
    op, p = node["op"], node["params"]
    if op == "map":
        return parent.map(_resolve_fn(p["fn"]), name=p["name"],
                          workers=p["workers"], on_error=p["on_error"],
                          span=p["span"], **_stage_knobs(p, sync))
    if op == "batch":
        return parent.batch(p["batch_size"],
                            drop_remainder=p["drop_remainder"])
    if op == "shuffle":
        return parent.shuffle(p["buffer_size"], seed=p["seed"])
    if op == "interleave":
        return parent.interleave(_resolve_fn(p["sub_fn"]),
                                 cycle_length=p["cycle_length"],
                                 block_length=p["block_length"])
    if op == "prefetch":
        return parent.prefetch(-1 if sync else p["depth"], name=p["name"])
    if op == "skip":
        return parent.skip(p["n"])
    if op == "take":
        return parent.take(p["n"])
    if op == "snapshot":
        return parent.snapshot(p["tag"])
    raise GraphSerializationError(f"unknown graph op {op!r}")


def from_spec(spec: dict, *, sync: bool = False) -> Dataset:
    """Rebuild an executable Dataset from a spec dict.  `sync=True`
    forces every staged op inline (no pools) — inproc worker mode."""
    _check_version(spec)
    return _build_node(spec["root"], sync=sync)


def loads(text: str, *, sync: bool = False) -> Dataset:
    return from_spec(json.loads(text), sync=sync)


# ops whose output index i maps 1:1 to input index i, so an output
# range pushes through unchanged
_INDEX_PRESERVING = ("prefetch",)


def build_range(spec: dict, start: int, stop: int, *,
                sync: bool = False) -> Dataset:
    """Build the plan restricted to output elements [start, stop) — one
    service split.  See module docstring for the pushdown rules."""
    _check_version(spec)
    if not (0 <= start <= stop):
        raise ValueError(f"bad range [{start}, {stop})")
    node: Optional[dict] = spec["root"]
    pushed: list[dict] = []
    lo, hi = start, stop
    while node is not None:
        op, p = node["op"], node["params"]
        if op == "map" and p["on_error"] != "skip":
            pushed.append(node)           # 1:1 (column wraps, never drops)
        elif op in _INDEX_PRESERVING:
            pushed.append(node)
        elif op == "snapshot":
            pass  # identity in a worker: consumed-offset counting is a
            # consumer-side concern; per-split counts are meaningless
        elif op == "batch":
            pushed.append(node)           # batch i <- elements [i*bs,(i+1)*bs)
            bs = p["batch_size"]
            lo, hi = lo * bs, hi * bs
        else:
            break                         # barrier: replay seeded stream
        node = node.get("parent")
    ds = _build_node(node, sync=sync).skip(lo).take(hi - lo)
    for n in reversed(pushed):
        ds = _apply_node(n, ds, sync=sync)
    return ds
