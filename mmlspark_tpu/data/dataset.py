"""Composable streaming Dataset graph: the ingestion path as a pipeline.

BENCH_r05 measured resnet50 scoring at ~1% of the device's capability —
the host decode/stage/transfer path, hand-tuned as a single `Prefetcher`
window, was the whole story.  This module replaces that single window
with the tf.data construction (Murray et al., arXiv:2101.12127): a
declarative graph of sources and ops —

    ds = (Dataset.from_files("imgs/**/*.png")
            .batch(256)
            .map(decode_chunk, name="decode")
            .prefetch())
    with ds.iterator() as it:
        for chunk in it:
            ...

— where `map` runs its function on parallel workers (order-preserving),
`prefetch` decouples producer from consumer with a bounded buffer, and
every parallel stage is an `executor.map_runner` Prefetcher underneath,
so the repo's existing contracts (deterministic ordering, backpressure,
exception-at-position, clean shutdown) hold stage by stage.

Graphs are *plans*: each op closes over its parent and nothing executes
until `iterator()` builds the chain.  Building is eager per stage (the
runners exist immediately, so the `Autotuner` can see them) but pulling
is lazy (no source item is read before the first `next`).  Stage depths
follow the shared knob contract (`resolve_depth`): positive pins, 0
autotunes from the floor, negative is synchronous.  When any stage asked
for autotuning, the iterator runs an `Autotuner` (data/autotune.py) over
those stages, re-sizing staged windows from measured stall/residency
counters and publishing `data.autotune` telemetry.

Row-level error policy on `map` reuses the shared `on_error` contract
(core/pipeline.py): "fail" re-raises at the failed item's position,
"skip" drops the row and reports it through `record_skipped_rows`, and
"column" keeps the row as a `MapError(item, error)` so the consumer can
materialize an error column.

Every source and op additionally records a declarative `_spec` node
(op name, raw params, parent) alongside its closure.  The spec is what
`data/graph.py` serializes so a disaggregated service worker
(`data/service/`) can rebuild and execute the same plan in another
process; `distribute()` splices that service into the chain and
`snapshot(tag)` exposes a consumed-element offset for mid-epoch
checkpoint/resume (data/snapshot.py).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Iterator, Optional

from mmlspark_tpu import config
from mmlspark_tpu.core.pipeline import check_on_error, record_skipped_rows
from mmlspark_tpu.data import executor
from mmlspark_tpu.data.autotune import Autotuner
from mmlspark_tpu.observe.spans import active_timings, span_on
from mmlspark_tpu.parallel.prefetch import resolve_depth

_OK = object()   # map wrapper tags (identity-compared, never user-visible)
_ERR = object()


class MapError:
    """A row that failed `map` under on_error="column": carries the input
    item and the exception, in the row's original stream position."""

    __slots__ = ("item", "error")

    def __init__(self, item, error: BaseException):
        self.item = item
        self.error = error

    def __repr__(self):
        return f"MapError({type(self.error).__name__}: {self.error})"


class _StageHandle:
    """One executing parallel stage: its graph name, the live Prefetcher
    runner, and whether the depth knob asked for autotuning."""

    def __init__(self, name: str, runner, tunable: bool):
        self.name = name
        self.runner = runner
        self.tunable = tunable


def _stage_depth(depth, tunable_default_workers=False):
    """Resolve one stage's depth knob -> (depth, autotune, max_depth,
    workers_default).  Tunable stages get headroom up to
    MMLSPARK_TPU_DATA_MAX_DEPTH and a pool wide enough that widening the
    window recruits more workers."""
    d, tune = resolve_depth(depth)
    if not tune:
        return d, False, None, None
    cap = max(d, int(config.get("MMLSPARK_TPU_DATA_MAX_DEPTH")))
    workers = max(1, int(config.get("MMLSPARK_TPU_DATA_MAX_WORKERS")))
    return d, True, cap, workers


class Dataset:
    """A lazily-evaluated pipeline plan.  Ops return new Datasets; no
    work happens until `iterator()` (or plain `for ... in ds`)."""

    def __init__(self, make_iter: Callable[["DatasetIterator"], Iterator],
                 name: str, spec: Optional[tuple] = None):
        self._make_iter = make_iter
        self._name = name
        # (op, params, parent Dataset | None); None marks the node as not
        # serializable for service execution (from_table, distribute)
        self._spec = spec

    # -- sources --------------------------------------------------------
    @staticmethod
    def from_iterable(items, name: str = "iterable") -> "Dataset":
        """Wrap an iterable — or a zero-arg callable returning one, which
        makes the dataset re-iterable — as a source."""
        def make(it):
            return iter(items() if callable(items) else items)
        return Dataset(make, name,
                       spec=("iterable", {"items": items, "name": name},
                             None))

    @staticmethod
    def from_files(path: str, *, recursive: bool = False,
                   sample_ratio: float = 1.0, inspect_zip: bool = True,
                   pattern: Optional[str] = None, seed: int = 0,
                   name: str = "files") -> "Dataset":
        """Stream `(path, bytes)` pairs from a directory/glob/zip via
        `io.files.iter_binary_files` — enumeration and reads stay
        sequential on the pulling thread (ordering is part of the
        contract); parallelism comes from downstream `map`."""
        def make(it):
            from mmlspark_tpu.io.files import iter_binary_files
            return iter_binary_files(path, recursive=recursive,
                                     sample_ratio=sample_ratio,
                                     inspect_zip=inspect_zip,
                                     pattern=pattern, seed=seed)
        return Dataset(make, name,
                       spec=("files", {"path": path, "recursive": recursive,
                                       "sample_ratio": sample_ratio,
                                       "inspect_zip": inspect_zip,
                                       "pattern": pattern, "seed": seed,
                                       "name": name}, None))

    @staticmethod
    def from_table(table, columns: Optional[list] = None,
                   name: str = "table") -> "Dataset":
        """Stream a DataTable as per-row dicts of the selected columns
        (all columns by default), in row order."""
        def make(it):
            cols = list(columns) if columns is not None else table.columns
            arrays = {c: table[c] for c in cols}
            n = len(table)
            return ({c: arrays[c][i] for c in cols} for i in range(n))
        return Dataset(make, name)

    # -- ops ------------------------------------------------------------
    def map(self, fn: Callable[[Any], Any], *, name: str = "map",
            depth: Optional[int] = None, workers: Optional[int] = None,
            on_error: str = "fail",
            span: Optional[str] = "host") -> "Dataset":
        """Parallel per-element map: `fn` runs on worker threads, results
        are delivered strictly in input order regardless of completion
        order.  `depth` follows the shared knob contract (None = config,
        positive pins, 0 autotunes, negative = inline on the pulling
        thread).  `span` attributes worker time to a pipeline-timings
        stage (observe/spans.py); pass None when `fn` instruments itself.
        `on_error`: "fail" | "skip" | "column" (module docstring)."""
        check_on_error(on_error)
        parent = self

        def make(it):
            upstream = parent._make_iter(it)
            d, tune, cap, wdef = _stage_depth(depth)
            timings = it.timings
            if span is None:
                inner = fn
            else:
                def inner(item):
                    with span_on(timings, span):
                        return fn(item)
            if on_error == "fail":
                work = inner  # raw fn: Prefetcher's native
                # exception-at-position contract IS the fail policy
            else:
                def work(item):
                    try:
                        return _OK, inner(item)
                    except Exception as e:
                        return _ERR, (item, e)
            runner = executor.map_runner(
                work, upstream, depth=d,
                workers=workers if workers is not None else wdef,
                max_depth=cap, name=name)
            it.register(name, runner, tunable=tune)
            if on_error == "fail":
                return iter(runner)

            def gen():
                for tag, val in runner:
                    if tag is _OK:
                        yield val
                    elif on_error == "skip":
                        item, err = val
                        record_skipped_rows(
                            f"data.map.{name}", 1,
                            f"{type(err).__name__}: {err}")
                    else:  # column
                        yield MapError(*val)
            return gen()
        return Dataset(make, f"{self._name}.map({name})",
                       spec=("map", {"fn": fn, "name": name, "depth": depth,
                                     "workers": workers,
                                     "on_error": on_error, "span": span},
                             parent))

    def batch(self, batch_size: int,
              drop_remainder: bool = False) -> "Dataset":
        """Group consecutive elements into lists of `batch_size` (the
        final short batch is kept unless drop_remainder)."""
        if batch_size <= 0:
            raise ValueError(
                f"batch_size must be positive, got {batch_size}")
        parent = self

        def make(it):
            upstream = parent._make_iter(it)

            def gen():
                buf: list = []
                for item in upstream:
                    buf.append(item)
                    if len(buf) >= batch_size:
                        yield buf
                        buf = []
                if buf and not drop_remainder:
                    yield buf
            return gen()
        return Dataset(make, f"{self._name}.batch",
                       spec=("batch", {"batch_size": batch_size,
                                       "drop_remainder": drop_remainder},
                             parent))

    def shuffle(self, buffer_size: int, *, seed: int = 0) -> "Dataset":
        """Seeded windowed shuffle: a `buffer_size` reservoir is kept
        full and each pull swaps out a seeded-random slot.  The order is
        a pure function of (seed, input order), so every fresh iteration
        replays identically — resume is re-iterate + `skip(consumed)`,
        the same replay discipline as Trainer's epoch orders."""
        if buffer_size <= 0:
            raise ValueError(
                f"buffer_size must be positive, got {buffer_size}")
        parent = self

        def make(it):
            upstream = parent._make_iter(it)

            def gen():
                rng = random.Random(seed)
                buf: list = []

                def pop():
                    i = rng.randrange(len(buf))
                    buf[i], buf[-1] = buf[-1], buf[i]
                    return buf.pop()
                for item in upstream:
                    buf.append(item)
                    if len(buf) >= buffer_size:
                        yield pop()
                while buf:
                    yield pop()
            return gen()
        return Dataset(make, f"{self._name}.shuffle",
                       spec=("shuffle", {"buffer_size": buffer_size,
                                         "seed": seed}, parent))

    def interleave(self, sub_fn: Callable[[Any], Any], *,
                   cycle_length: int, block_length: int = 1) -> "Dataset":
        """Fan-in over sharded sub-streams: `sub_fn(item)` opens a
        Dataset (or any iterable) per input element; `cycle_length` of
        them are open at once and served round-robin, `block_length`
        elements per visit.  When one ends, the next input element's
        stream takes its slot — deterministic given the input order."""
        if cycle_length <= 0:
            raise ValueError(
                f"cycle_length must be positive, got {cycle_length}")
        if block_length <= 0:
            raise ValueError(
                f"block_length must be positive, got {block_length}")
        parent = self

        def make(it):
            upstream = parent._make_iter(it)

            def open_sub(item):
                sub = sub_fn(item)
                if isinstance(sub, Dataset):
                    return sub._make_iter(it)  # sub-stages share plumbing
                return iter(sub)

            def gen():
                active: list = []
                for item in upstream:
                    active.append(open_sub(item))
                    if len(active) >= cycle_length:
                        break
                idx = 0
                while active:
                    if idx >= len(active):
                        idx = 0
                    ended = False
                    for _ in range(block_length):
                        try:
                            yield next(active[idx])
                        except StopIteration:
                            ended = True
                            break
                    if ended:
                        try:
                            active[idx] = open_sub(next(upstream))
                        except StopIteration:
                            active.pop(idx)
                    else:
                        idx += 1
            return gen()
        return Dataset(make, f"{self._name}.interleave",
                       spec=("interleave", {"sub_fn": sub_fn,
                                            "cycle_length": cycle_length,
                                            "block_length": block_length},
                             parent))

    def prefetch(self, depth: Optional[int] = None, *,
                 name: str = "prefetch") -> "Dataset":
        """Decouple producer from consumer with a bounded buffer: one
        background thread pulls upstream while the consumer works on
        earlier elements.  Same depth knob contract as `map`; depth that
        resolves to 0 makes this a passthrough.  Note the upstream is
        then pulled on the buffer thread — don't add `prefetch` below
        sources whose pulls must stay on the consumer thread (Trainer's
        rng-ordered plan)."""
        parent = self

        def make(it):
            upstream = parent._make_iter(it)
            d, tune, cap, _ = _stage_depth(depth)
            if d <= 0:
                return upstream

            def pull(_marker):
                try:
                    return True, next(upstream)
                except StopIteration:
                    return False, None
            # workers=1: a single buffer thread keeps upstream pulls
            # serialized, so ordering needs no further machinery
            runner = executor.map_runner(pull, itertools.repeat(None),
                                         depth=d, workers=1,
                                         max_depth=cap, name=name)
            it.register(name, runner, tunable=tune)

            def gen():
                for ok, val in runner:
                    if not ok:
                        break
                    yield val
                runner.close()
            return gen()
        return Dataset(make, f"{self._name}.prefetch",
                       spec=("prefetch", {"depth": depth, "name": name},
                             parent))

    def skip(self, n: int) -> "Dataset":
        """Drop the first `n` elements (the resume idiom: replay the
        seeded stream, skip what the previous run consumed)."""
        parent = self

        def make(it):
            upstream = parent._make_iter(it)
            return itertools.islice(upstream, max(0, int(n)), None)
        return Dataset(make, f"{self._name}.skip",
                       spec=("skip", {"n": n}, parent))

    def take(self, n: int) -> "Dataset":
        """Keep only the first `n` elements."""
        parent = self

        def make(it):
            upstream = parent._make_iter(it)
            return itertools.islice(upstream, max(0, int(n)))
        return Dataset(make, f"{self._name}.take",
                       spec=("take", {"n": n}, parent))

    def snapshot(self, tag: str = "default") -> "Dataset":
        """Count delivered elements into the process-wide snapshot
        registry (data/snapshot.py) under `tag`, so Trainer checkpoints
        can record a mid-epoch consumed-offset in their `.meta.json`
        sidecar.  On the next `iterator()` after `set_restore_offsets`,
        the recorded offset is replayed — via the service session's
        dispatch offset when this sits directly above `distribute()`
        (nothing skipped is ever produced), else by dropping the first
        `offset` elements of the seeded local stream."""
        parent = self

        def make(it):
            from mmlspark_tpu.data import snapshot as snapmod
            upstream = parent._make_iter(it)
            handle = snapmod.register(tag)
            pending = snapmod.take_restore(tag)
            if pending:
                svc = (it.stage("service")
                       if getattr(parent, "_service_direct", False) else None)
                if not (svc is not None
                        and getattr(svc.runner, "fast_forward",
                                    lambda n: False)(pending)):
                    upstream = itertools.islice(upstream, pending, None)
                handle.consumed = pending

            def gen():
                for item in upstream:
                    handle.consumed += 1
                    yield item
            return gen()
        return Dataset(make, f"{self._name}.snapshot",
                       spec=("snapshot", {"tag": tag}, parent))

    def distribute(self, service=None, *, workers: Optional[int] = None,
                   mode: Optional[str] = None, deterministic: bool = True,
                   consumer_index: int = 0, num_consumers: int = 1,
                   split_elems: Optional[int] = None,
                   name: str = "service") -> "Dataset":
        """Splice the disaggregated data service into the chain: the
        graph below this point is serialized (data/graph.py, eagerly —
        unserializable graphs fail here, not in a worker) and executed
        by service workers; this op streams their ready elements.

        `service` is a `data.service.DataService` (shared across
        iterators/consumers); None builds a private one from the
        `MMLSPARK_TPU_DATA_SERVICE_*` knobs.  `workers` follows the
        shared knob contract: None = config, positive pins the worker
        count, 0 lets the Autotuner scale workers from stall evidence,
        negative bypasses the service entirely (pure local execution).
        `deterministic=True` reassembles splits in index order so the
        epoch is byte-identical to local execution; False yields
        first-come (dynamic sharding).  `consumer_index`/`num_consumers`
        shard splits round-robin across consumers."""
        from mmlspark_tpu.data.graph import to_spec
        spec = to_spec(self)
        parent = self

        def make(it):
            from mmlspark_tpu.data.service import DataService
            from mmlspark_tpu.data.service.consume import ServiceConsumer
            svc = service
            if svc is None:
                w = (int(config.get("MMLSPARK_TPU_DATA_SERVICE_WORKERS"))
                     if workers is None else int(workers))
                if w < 0:
                    return parent._make_iter(it)
                svc = DataService(workers=w, mode=mode)
            runner = ServiceConsumer(
                svc, spec, deterministic=deterministic,
                consumer_index=consumer_index,
                num_consumers=num_consumers, split_elems=split_elems,
                owns_service=service is None)
            it.register(name, runner, tunable=runner.tunable)
            return iter(runner)
        ds = Dataset(make, f"{self._name}.distribute")
        ds._service_direct = True
        return ds

    # -- execution ------------------------------------------------------
    def iterator(self, *, autotune: Optional[bool] = None,
                 interval: Optional[int] = None) -> "DatasetIterator":
        """Build the executing chain.  `autotune=None` (default) runs
        the Autotuner iff some stage's depth knob asked for it; False
        forces it off (tunable stages stay at their floor); True is
        only meaningful with tunable stages present."""
        return DatasetIterator(self, autotune=autotune, interval=interval)

    def __iter__(self) -> "DatasetIterator":
        return self.iterator()


class DatasetIterator:
    """The executing side of a Dataset: iterate it, `close()` it (also
    via `with`), and inspect `stages` / `tuner` for live depths."""

    def __init__(self, dataset: Dataset, *,
                 autotune: Optional[bool] = None,
                 interval: Optional[int] = None):
        self._closed = False
        self.stages: list[_StageHandle] = []
        # captured HERE on the consumer thread: map workers never see the
        # timings contextvar (the same capture-by-closure rule as every
        # hot loop in the repo)
        self.timings = active_timings()
        self._it = dataset._make_iter(self)
        tunable = [s for s in self.stages if s.tunable]
        self.tuner = (Autotuner(tunable, interval=interval)
                      if tunable and autotune is not False else None)

    # called by op builders as the chain is assembled
    def register(self, name: str, runner, tunable: bool = False):
        self.stages.append(_StageHandle(name, runner, tunable))
        return runner

    def stage(self, name: str) -> Optional[_StageHandle]:
        for s in self.stages:
            if s.name == name:
                return s
        return None

    def __iter__(self) -> "DatasetIterator":
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        try:
            item = next(self._it)
        except BaseException:
            self.close()
            raise
        if self.tuner is not None:
            self.tuner.tick()
        return item

    def close(self) -> None:
        """Shut down every stage's pool (idempotent); sink-to-source so
        upstream runners stop feeding closed consumers."""
        if self._closed:
            return
        self._closed = True
        for s in reversed(self.stages):
            s.runner.close()

    def __enter__(self) -> "DatasetIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
