"""Streaming data layer: composable Dataset graphs with parallel map
workers, bounded prefetch buffers, span-driven autotuning, and a
disaggregated multi-process data service.

See data/dataset.py for the graph model, data/autotune.py for the
controller, data/executor.py for the one sanctioned thread-pool
construction point, data/graph.py for the serialized graph spec,
data/service/ for the worker tier (docs/data-service.md), and
docs/performance.md ("Streaming data layer").
"""

from mmlspark_tpu.data.autotune import Autotuner
from mmlspark_tpu.data.dataset import Dataset, DatasetIterator, MapError

__all__ = ["Autotuner", "Dataset", "DatasetIterator", "MapError"]
