"""Streaming data layer: composable Dataset graphs with parallel map
workers, bounded prefetch buffers, and span-driven autotuning.

See data/dataset.py for the graph model, data/autotune.py for the
controller, data/executor.py for the one sanctioned thread-pool
construction point, and docs/performance.md ("Streaming data layer").
"""

from mmlspark_tpu.data.autotune import Autotuner
from mmlspark_tpu.data.dataset import Dataset, DatasetIterator, MapError

__all__ = ["Autotuner", "Dataset", "DatasetIterator", "MapError"]
