"""Mid-epoch snapshot offsets: the bridge between `Dataset.snapshot`
and PR 7's checkpoint `.meta.json` sidecar.

A `snapshot(tag)` op counts elements it has delivered this iteration
into a process-wide registry.  `snapshot_offsets()` is what Trainer
folds into `_ckpt_meta` at checkpoint time ("7 chunks of epoch 3 were
consumed"); on elastic resume the saved offsets come back through
`set_restore_offsets`, and the next `iterator()` build of each tagged
dataset replays exactly the remaining sequence — through the service
session's dispatch offset when distributed (skipped elements are never
produced), or by dropping the first `offset` elements of the seeded
local stream (the same replay discipline as Trainer's epoch orders).

Offsets are plain advisory ints, like everything else in the sidecar:
a missing or stale tag degrades to a fresh epoch, never a crash.
"""

from __future__ import annotations

from typing import Optional


class SnapshotHandle:
    """Live consumed-element counter for one tagged snapshot op."""

    __slots__ = ("tag", "consumed")

    def __init__(self, tag: str):
        self.tag = tag
        self.consumed = 0


_handles: dict[str, SnapshotHandle] = {}
_restore: dict[str, int] = {}


def register(tag: str) -> SnapshotHandle:
    """Called by `Dataset.snapshot` at iterator build: a fresh handle
    (consumed=0) replaces any previous iteration's counter."""
    h = SnapshotHandle(tag)
    _handles[tag] = h
    return h


def snapshot_offsets() -> dict[str, int]:
    """Current consumed-offset per live tag — checkpoint-meta payload."""
    return {t: h.consumed for t, h in _handles.items()}


def set_restore_offsets(offsets: Optional[dict]) -> None:
    """Stage saved offsets (from a checkpoint's meta sidecar) to be
    applied by the NEXT iterator build of each tagged dataset."""
    if not offsets:
        return
    for tag, off in offsets.items():
        try:
            n = int(off)
        except (TypeError, ValueError):
            continue
        if n > 0:
            _restore[str(tag)] = n


def take_restore(tag: str) -> int:
    """Consume (one-shot) the pending restore offset for `tag`, 0 if
    none — each staged offset fast-forwards exactly one build."""
    return _restore.pop(tag, 0)


def clear() -> None:
    """Drop all handles and pending restores (test isolation)."""
    _handles.clear()
    _restore.clear()
