"""Span-driven autotuning for Dataset stages (the tf.data move).

PR 6's roofline analytics issue "host-bound" verdicts that nothing acts
on; this closes the loop.  Every parallel stage's `Prefetcher` keeps
always-on counters — deliveries, stalls (pulls that blocked on an
unfinished future), cumulative stall seconds, and queue residency.  The
`Autotuner` samples those counters every `interval` sink pulls and turns
the window deltas into depth decisions:

  * **widen the bottleneck** — the stage whose window spent the most
    wall time stalling the consumer (stall fraction above
    WIDEN_STALL_FRAC) gets its staged window grown ~1.5x, up to
    MMLSPARK_TPU_DATA_MAX_DEPTH.  A deeper window admits more concurrent
    map workers (effective workers = min(depth, pool width)), so this is
    both the depth and the worker-count lever.
  * **back off on slack** — a stage that never stalls and whose queue
    rides near-full (residency above NARROW_RESIDENCY_FRAC of capacity)
    is producing faster than it is consumed; its window shrinks by one,
    never below the floor (`DEPTH_FLOOR`, see parallel/prefetch.py),
    releasing memory and threads to the actual bottleneck.

Decisions are published while a telemetry run is active: per-stage
`data.<stage>.depth` / `.stall_frac` gauges and a `data.autotune` trace
event stream (cat="data"), so a run-report shows what the tuner did and
why.  The controller itself is pure arithmetic over counter snapshots —
tests drive it with synthetic stage stats, no clocks, no sleeps.

The same controller scales the disaggregated data service: a
`ServiceConsumer` stage (data/service/consume.py) exposes the identical
`stats()/depth/max_depth/set_depth` surface where depth counts *worker
processes* — a runner declares that by setting `scale_unit = "workers"`
(gauges publish as `data.<stage>.workers`) and may pin its own lower
bound with `depth_floor` (a fleet narrows to one worker, not to
DEPTH_FLOOR staged slots).
"""

from __future__ import annotations

from typing import Optional

from mmlspark_tpu import config
from mmlspark_tpu.parallel.prefetch import DEPTH_FLOOR

AUTOTUNE_INTERVAL = config.register(
    "MMLSPARK_TPU_DATA_AUTOTUNE_INTERVAL", default=32, ptype=int,
    doc="Sink pulls between Dataset autotune decisions: each interval "
        "the tuner reads per-stage stall/residency counter windows and "
        "may widen the bottleneck stage or narrow a slack one.")

DATA_MAX_DEPTH = config.register(
    "MMLSPARK_TPU_DATA_MAX_DEPTH", default=64, ptype=int,
    doc="Ceiling on any autotuned Dataset stage's staged-window depth "
        "(bounds host RAM held in staged batches; pinned depths are "
        "not clamped).")

DATA_MAX_WORKERS = config.register(
    "MMLSPARK_TPU_DATA_MAX_WORKERS", default=16, ptype=int,
    doc="Thread-pool width of an autotuned map stage; effective "
        "concurrency is min(depth, this), so widening the staged window "
        "recruits more workers up to this cap.")


class Autotuner:
    """Depth controller over a Dataset iterator's tunable stages.

    `stages` is a list of handles exposing `.name` and `.runner`, where
    the runner has the Prefetcher tuning surface: `stats()`, `depth`,
    `max_depth`, `set_depth()`.  Call `tick()` once per sink delivery;
    every `interval` ticks it takes a `step()` (callable directly in
    tests, no wall-clock involved).
    """

    WIDEN_STALL_FRAC = 0.25     # >25% of pulls blocked -> starved consumer
    NARROW_STALL_FRAC = 0.05    # <5% blocked -> stage is keeping up
    NARROW_RESIDENCY_FRAC = 0.5  # queue >half full on average -> slack

    def __init__(self, stages, *, interval: Optional[int] = None,
                 floor: Optional[int] = None):
        from mmlspark_tpu.observe.telemetry import active_run
        self._stages = list(stages)
        self._interval = max(1, int(
            interval if interval is not None
            else config.get("MMLSPARK_TPU_DATA_AUTOTUNE_INTERVAL")))
        self._floor = max(1, int(floor if floor is not None else DEPTH_FLOOR))
        self._pulls = 0
        self._last = {id(s): s.runner.stats() for s in self._stages}
        self._run = active_run()
        self.decisions: list = []  # every applied change, for inspection

    # -- cadence --------------------------------------------------------
    def tick(self) -> None:
        self._pulls += 1
        if self._pulls % self._interval == 0:
            self.step()

    # -- one decision ---------------------------------------------------
    def step(self) -> list:
        """Read each stage's counter window since the last step and apply
        at most one widen (the bottleneck) plus any back-offs; returns
        the decisions made this step."""
        windows = []
        for s in self._stages:
            cur = s.runner.stats()
            prev = self._last[id(s)]
            self._last[id(s)] = cur
            delta = {k: cur[k] - prev[k]
                     for k in ("deliveries", "stalls", "stall_s",
                               "residency")}
            if delta["deliveries"] <= 0:
                continue  # stage idle this window: no evidence either way
            stall_frac = delta["stalls"] / delta["deliveries"]
            residency_frac = (delta["residency"]
                              / (delta["deliveries"]
                                 * max(1, s.runner.depth)))
            windows.append((s, stall_frac, residency_frac, delta))
            if self._run is not None:
                self._run.gauge(
                    f"data.{s.name}.{self._unit(s)}", s.runner.depth)
                self._run.gauge(f"data.{s.name}.stall_frac",
                                round(stall_frac, 4))

        made = []
        # widen exactly one stage per step — the one the consumer lost the
        # most wall time to — so depth changes stay attributable and the
        # next window measures their effect in isolation
        starved = [(d["stall_s"], sf, s) for s, sf, _, d in windows
                   if sf > self.WIDEN_STALL_FRAC
                   and s.runner.depth < s.runner.max_depth]
        if starved:
            stall_s, sf, s = max(starved, key=lambda t: (t[0], t[1]))
            old = s.runner.depth
            new = s.runner.set_depth(old + max(1, old // 2))
            if new != old:
                made.append(self._publish(s, "widen", old, new, sf))
        for s, sf, rf, _ in windows:
            floor = self._floor_for(s)
            if (sf < self.NARROW_STALL_FRAC
                    and rf > self.NARROW_RESIDENCY_FRAC
                    and s.runner.depth > floor):
                old = s.runner.depth
                new = s.runner.set_depth(max(floor, old - 1))
                if new != old:
                    made.append(self._publish(s, "narrow", old, new, sf))
        self.decisions.extend(made)
        return made

    @staticmethod
    def _unit(stage) -> str:
        return getattr(stage.runner, "scale_unit", "depth")

    def _floor_for(self, stage) -> int:
        floor = getattr(stage.runner, "depth_floor", None)
        return max(1, int(floor)) if floor is not None else self._floor

    def _publish(self, stage, action: str, old: int, new: int,
                 stall_frac: float) -> dict:
        from mmlspark_tpu.observe.trace import trace_event
        unit = self._unit(stage)
        decision = {"stage": stage.name, "action": action, "unit": unit,
                    "depth_from": old, "depth_to": new,
                    "stall_frac": round(stall_frac, 4)}
        trace_event("data.autotune", cat="data", **decision)
        if self._run is not None:
            self._run.gauge(f"data.{stage.name}.{unit}", new)
        return decision
