"""The data layer's ONE thread-pool construction point.

Every parallel stage in a `Dataset` graph — `map` workers, `prefetch`
buffers — executes on a `Prefetcher` (parallel/prefetch.py): the
order-preserving bounded background map that already carries the repo's
backpressure, exception-at-position, and clean-shutdown contracts.  This
module is the only place in `mmlspark_tpu/data/` or `mmlspark_tpu/io/`
allowed to construct one (scripts/lint.py enforces it, the same move as
serve/'s lifecycle-only thread rule): keeping pool construction in one
file is what keeps "how many threads does ingestion own?" a one-file
audit, and what lets the Autotuner assume every stage exposes the
Prefetcher counter/`set_depth` surface.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

from mmlspark_tpu.parallel.prefetch import Prefetcher


def map_runner(fn: Callable[[Any], Any], items: Iterable, *, depth: int,
               workers: Optional[int] = None,
               max_depth: Optional[int] = None,
               name: str = "map") -> Prefetcher:
    """Build the executing stage for a parallel map: `fn` runs on worker
    threads over `items`, results return in item order, at most `depth`
    staged-but-unconsumed (live-tunable up to `max_depth`).  `depth=0`
    is the synchronous inline mode (no threads)."""
    return Prefetcher(fn, items, depth=depth, workers=workers,
                      max_depth=max_depth, name=name)
