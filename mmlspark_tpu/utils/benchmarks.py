"""The learner-grid metric benchmark.

Counterpart of the reference's committed metric regression net
(VerifyTrainClassifier.scala:36-37,203-216 + benchmarkMetrics.csv): every
learner family trained on every grid dataset, metrics rounded and diffed
EXACTLY against a committed CSV.  Regeneration is deliberate:

    python scripts/regen_benchmarks.py

after any change that legitimately moves the numbers; the test fails on any
unintentional drift.
"""

from __future__ import annotations

import io

import numpy as np


def _learners():
    from mmlspark_tpu.ml import (DecisionTreeClassifier, GBTClassifier,
                                 LogisticRegression,
                                 MultilayerPerceptronClassifier, NaiveBayes,
                                 RandomForestClassifier)
    return {
        "LogisticRegression": lambda: LogisticRegression(),
        "DecisionTreeClassifier": lambda: DecisionTreeClassifier(maxDepth=5),
        "RandomForestClassifier": lambda: RandomForestClassifier(
            numTrees=10, maxDepth=5),
        "GBTClassifier": lambda: GBTClassifier(maxIter=10, maxDepth=4),
        "NaiveBayes": lambda: NaiveBayes(),
        "MultilayerPerceptronClassifier":
            lambda: MultilayerPerceptronClassifier(layers=[-1, 16, -1],
                                                   maxIter=30, seed=0),
    }


def compute_learner_grid(dataset: "str | None" = None) -> list[dict]:
    """accuracy (+AUC when binary) for every (dataset, learner) pair.

    `dataset` limits computation to one grid dataset — the per-dataset
    parametrized tests use this so no single test carries the whole grid's
    runtime (round-3 verdict weak #6)."""
    from mmlspark_tpu.ml import ComputeModelStatistics, TrainClassifier
    from mmlspark_tpu.utils.demo_data import grid_datasets

    rows = []
    for ds_name, table in grid_datasets().items():
        if dataset is not None and ds_name != dataset:
            continue
        label = "income" if "income" in table.columns else "label"
        n_train = int(table.num_rows * 0.75)
        train = table.slice(0, n_train)
        test = table.slice(n_train, table.num_rows)
        for learner_name, make in _learners().items():
            # NB needs non-negative features; skip it off the raw-numeric
            # datasets with negative values (the reference grid also runs
            # each learner only where it applies)
            if learner_name == "NaiveBayes" and ds_name != "census_mixed":
                continue
            # binary-only, as in the reference (TrainClassifier.scala:101-104)
            if learner_name == "GBTClassifier" and \
                    len(set(np.asarray(table[label]).tolist())) > 2:
                continue
            model = TrainClassifier(make(), labelCol=label).fit(train)
            metrics = ComputeModelStatistics().transform(
                model.transform(test))
            row = {"dataset": ds_name, "learner": learner_name,
                   "accuracy": round(float(metrics["accuracy"][0]), 6)}
            row["AUC"] = (round(float(metrics["AUC"][0]), 6)
                          if "AUC" in metrics.columns else "")
            rows.append(row)
    return rows


def grid_to_csv(rows: list[dict]) -> str:
    buf = io.StringIO()
    buf.write("dataset,learner,accuracy,AUC\n")
    for r in rows:
        buf.write(f"{r['dataset']},{r['learner']},{r['accuracy']},{r['AUC']}\n")
    return buf.getvalue()
