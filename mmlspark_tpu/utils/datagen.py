"""Random table generation for fuzz testing.

TPU-native counterpart of the reference's datagen component
(GenerateDataset.scala:17-114, DatasetOptions.scala): generate DataTables
over a space of column types (numeric scalar/vector, string, categorical,
boolean, image) with controllable missing-value rates, driving the
generic stage fuzzing suite (reference Fuzzing.scala:49-104).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from mmlspark_tpu.core.schema import make_categorical
from mmlspark_tpu.core.table import DataTable, object_column

COLUMN_KINDS = ("double", "int", "bool", "string", "vector", "categorical",
                "image")


@dataclasses.dataclass
class ColumnOptions:
    """Which column kinds to generate and how."""

    kinds: Sequence[str] = COLUMN_KINDS[:6]  # image opt-in (big)
    missing_ratio: float = 0.0
    vector_width: int = 4
    num_levels: int = 3
    image_hw: tuple = (8, 8)


def generate_table(num_rows: int = 20, num_cols: int = 4,
                   options: Optional[ColumnOptions] = None,
                   seed: int = 0,
                   with_label: bool = True) -> DataTable:
    """A random table cycling through the configured column kinds."""
    opts = options or ColumnOptions()
    rng = np.random.default_rng(seed)
    cols: dict = {}
    for i in range(num_cols):
        kind = opts.kinds[i % len(opts.kinds)]
        name = f"{kind}_{i}"
        cols[name] = _gen_column(kind, num_rows, opts, rng)
    if with_label:
        cols["label"] = rng.integers(0, 2, num_rows).astype(np.int64)
    table = DataTable(cols)
    for name in list(table.columns):
        if name.startswith("categorical_"):
            table = make_categorical(table, name)
    return table


def _gen_column(kind: str, n: int, opts: ColumnOptions,
                rng: np.random.Generator):
    if kind == "double":
        vals = rng.normal(size=n)
        if opts.missing_ratio > 0:
            vals[rng.random(n) < opts.missing_ratio] = np.nan
        return vals
    if kind == "int":
        return rng.integers(-100, 100, n).astype(np.int64)
    if kind == "bool":
        return rng.integers(0, 2, n).astype(np.bool_)
    if kind == "string":
        words = ["alpha", "beta", "gamma", "delta", "epsilon"]
        out = [" ".join(rng.choice(words, size=rng.integers(1, 4)))
               for _ in range(n)]
        if opts.missing_ratio > 0:
            out = [None if rng.random() < opts.missing_ratio else v
                   for v in out]
        return object_column(out)
    if kind == "vector":
        return rng.normal(size=(n, opts.vector_width)).astype(np.float32)
    if kind == "categorical":
        return object_column(
            [f"level{int(i)}" for i in rng.integers(0, opts.num_levels, n)])
    if kind == "image":
        h, w = opts.image_hw
        return rng.integers(0, 255, size=(n, h, w, 3), dtype=np.uint8)
    raise ValueError(f"unknown column kind '{kind}'")
