"""Performance accounting: model FLOPs, chip peak, and MFU.

The reference has no performance accounting at all (its only timing is the
test-suite alert budget, TestBase.scala:65,146-153); scoring throughput was
whatever the per-partition JNI loop delivered.  A TPU framework lives or dies
by how much of the MXU it uses, so FLOPs/MFU are first-class here: `bench.py`
reports an `mfu` field, and regressions are visible instead of anecdotal.

MFU = achieved FLOP/s / chip peak FLOP/s (the "model FLOPs utilization" of
the scaling-book recipe): achieved = analytic forward FLOPs x images/sec;
peak from the device-kind table below (bf16 systolic-array peak).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np

# bf16 peak FLOP/s per chip by device kind (public spec sheets).  Keys are
# matched as lowercase substrings of jax's Device.device_kind.
_PEAK_BF16: list[tuple[str, float]] = [
    ("v6e", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


# HBM peak bandwidth (bytes/sec) per chip by device kind (public spec
# sheets), same substring matching as _PEAK_BF16.  Used by the decode
# bench's steady-step bandwidth model (kv_bytes_per_step / step time vs
# this peak = hbm_bw_util): the KV-cache read is the bandwidth-bound
# step's dominant traffic, so its utilization attributes cache-dtype wins.
_PEAK_HBM_BPS: list[tuple[str, float]] = [
    ("v6e", 1.64e12), ("trillium", 1.64e12),
    ("v5p", 2.765e12),
    ("v5 lite", 8.19e11), ("v5e", 8.19e11), ("v5litepod", 8.19e11),
    ("v4", 1.2288e12),
    ("v3", 9.0e11),
    ("v2", 7.0e11),
]


def device_peak_hbm_bw(device: Optional[Any] = None) -> Optional[float]:
    """HBM peak bytes/sec for `device` (default: first device); None if
    unknown (CPU / unrecognized kinds) — callers should then omit
    bandwidth-utilization fields rather than fabricate them."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, bw in _PEAK_HBM_BPS:
        if key in kind:
            return bw
    return None


def device_peak_flops(device: Optional[Any] = None) -> Optional[float]:
    """bf16 peak FLOP/s for `device` (default: first device); None if unknown
    (CPU / unrecognized kinds) — callers should then omit MFU rather than
    fabricate it."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def forward_flops(bundle, input_shape: tuple, dtype=np.float32) -> Optional[float]:
    """Analytic forward-pass FLOPs for one batch of `input_shape` through the
    bundle's module, from XLA's compiled cost analysis.  Returns None when the
    backend provides no cost model."""
    module = bundle.module()

    def fwd(v, x):
        out, _ = module.apply(v, x, mutable=["intermediates"])
        return out

    var_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        bundle.variables)
    try:
        compiled = jax.jit(fwd).lower(
            var_shapes, jax.ShapeDtypeStruct(input_shape, dtype)).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        return float(flops) if flops else None
    except Exception:
        return None


def lm_train_flops(batch: int, seq: int, d_model: int, n_layers: int,
                   vocab_size: int, *, causal: bool = True,
                   attn_impl: str = "flash", mlp_ratio: int = 4) -> dict:
    """Analytic TransformerLM train-step FLOPs, split so the XLA
    cross-check is well-defined (the ONE accounting bench.py and the
    perf-floor tests share).

      * `dense` — 6 x tokens x N_linear (fwd + 2x bwd over the QKVO
        projections, the MLP pair, and the vocab head);
      * `attn` — the mathematically REQUIRED attention matmuls: 2 forward
        (QK^T, PV) + 4 backward (dV = P^T dO, dP = dO V^T, dQ = dS K,
        dK = dS^T Q), each 2*B*S^2*d FLOPs dense, HALVED under a causal
        mask (only the lower triangle is required work).  Kernel-side
        recompute — the split flash backward re-issuing S and dP — is
        overhead, not useful work, and is NOT counted: reported MFU stays
        conservative relative to hardware utilization;
      * `total` = dense + attn — the MFU denominator's numerator;
      * `xla_visible` — what `compiled.cost_analysis()` can see: pallas
        kernels are opaque to XLA, so the flash path's visible FLOPs are
        the dense part alone; a dense attn_impl EXECUTES the full (and
        fully counted) S^2 matmuls, mask or no mask.

    `xla_flops / xla_visible` ≈ 1 is the agreement check that keeps the
    analytic model honest (test_perf_floor.py); the old single-number
    comparison read the pallas blindness as a mystery ~40% discrepancy
    on the 8k arm.
    """
    n_linear = (n_layers * (4 + 2 * mlp_ratio) * d_model * d_model
                + d_model * vocab_size)
    dense = 6 * batch * seq * n_linear
    attn_full = 6 * 2 * n_layers * batch * seq * seq * d_model
    attn = attn_full // 2 if causal else attn_full
    xla_visible = dense if attn_impl == "flash" else dense + attn_full
    return {"dense": dense, "attn": attn, "attn_full": attn_full,
            "total": dense + attn, "xla_visible": xla_visible}


def mfu(images_per_sec: float, flops_per_image: Optional[float],
        device: Optional[Any] = None) -> Optional[float]:
    """Model-FLOPs utilization of one chip at `images_per_sec`; None when
    either the FLOP count or the chip peak is unknown."""
    peak = device_peak_flops(device)
    if peak is None or not flops_per_image:
        return None
    return images_per_sec * flops_per_image / peak
