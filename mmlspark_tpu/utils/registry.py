"""Stage discovery: reflection over the package.

TPU-native counterpart of the reference's JarLoadingUtils
(JarLoadingUtils.scala:115-137): where the reference scans built jars for
every Transformer/Estimator/MLReadable to drive fuzzing and PySpark
wrapper codegen, here a package walk imports every module under
mmlspark_tpu and collects the PipelineStage subclasses.  The same registry
powers the fuzzing suite (tests/test_fuzzing.py) and the generated API
reference (api_summary — the codegen role collapses to introspection since
the core is already Python, SURVEY §7 stage 7).
"""

from __future__ import annotations

import importlib
import inspect
import pkgutil
from typing import Callable, Optional

import mmlspark_tpu
from mmlspark_tpu.core.pipeline import Estimator, PipelineStage, Transformer

_SKIP_MODULES = ("mmlspark_tpu.native_loader",)


def _walk_modules():
    for info in pkgutil.walk_packages(mmlspark_tpu.__path__,
                                      prefix="mmlspark_tpu."):
        if info.name in _SKIP_MODULES:
            continue
        yield importlib.import_module(info.name)


def all_stage_classes(concrete_only: bool = True) -> list[type]:
    """Every PipelineStage subclass defined in the package."""
    seen: dict[str, type] = {}
    for module in _walk_modules():
        for _, obj in inspect.getmembers(module, inspect.isclass):
            if not issubclass(obj, PipelineStage):
                continue
            if not obj.__module__.startswith("mmlspark_tpu"):
                continue
            key = f"{obj.__module__}.{obj.__qualname__}"
            seen[key] = obj
    out = []
    for cls in seen.values():
        if concrete_only:
            if inspect.isabstract(cls):
                continue
            # base plumbing classes are not user stages
            if cls.__module__ == "mmlspark_tpu.core.pipeline":
                continue
            # underscore-prefixed classes are private bases
            if cls.__qualname__.split(".")[-1].startswith("_"):
                continue
        out.append(cls)
    return sorted(out, key=lambda c: f"{c.__module__}.{c.__qualname__}")


# --------------------------------------------------------------------------
# Quantized module wrappers (quant/ subsystem).
#
# Maps a flax layer class to the fused quantized forward that replaces its
# `__call__` when the layer's param dict carries int8 weights + per-channel
# scales (`kernel` int8 with a `kernel_scale` sibling).  quant/modules.py
# registers the nn.Dense / nn.Conv wrappers at import; custom layers opt
# into int8 scoring by registering their own — the same open-registry
# discipline as MODEL_REGISTRY (models/definitions.py) and the stage walk
# above.  The lookup walks the MRO so subclasses of a registered layer
# inherit its wrapper.
# --------------------------------------------------------------------------

QUANT_MODULE_WRAPPERS: dict[type, Callable] = {}


def register_quant_wrapper(module_cls: type, wrapper: Callable) -> None:
    """Register the fused int8 forward for a flax layer class.

    `wrapper(module, x, kernel_q, kernel_scale, bias)` receives the BOUND
    layer instance (its hyperparameters: strides, padding, dtype, ...), the
    activation, the int8 kernel, the per-output-channel float32 scales, and
    the bias (or None) — and returns what the layer's `__call__` would.
    """
    QUANT_MODULE_WRAPPERS[module_cls] = wrapper


def quant_wrapper_for(module_cls: type) -> Optional[Callable]:
    """The registered wrapper for `module_cls` (MRO-aware), or None."""
    for cls in module_cls.__mro__:
        if cls in QUANT_MODULE_WRAPPERS:
            return QUANT_MODULE_WRAPPERS[cls]
    return None


def api_summary() -> str:
    """Markdown API reference generated from the registry + param docs
    (the PySparkWrapperGenerator's documentation role,
    PySparkWrapperGenerator.scala:34-91)."""
    lines = ["# mmlspark_tpu API reference", ""]
    for cls in all_stage_classes():
        kind = ("Estimator" if issubclass(cls, Estimator)
                else "Transformer" if issubclass(cls, Transformer)
                else "PipelineStage")
        lines.append(f"## {cls.__qualname__} ({kind})")
        lines.append(f"`{cls.__module__}`")
        doc = inspect.getdoc(cls)
        if doc:
            lines.append("")
            lines.append(doc.split("\n\n")[0])
        params = cls.params()
        if params:
            lines.append("")
            lines.append("| param | default | doc |")
            lines.append("|---|---|---|")
            for name, p in sorted(params.items()):
                default = repr(p.default) if p.has_default else "(required)"
                lines.append(f"| `{name}` | `{default}` | {p.doc} |")
        lines.append("")
    lines.extend(_config_summary())
    return "\n".join(lines)


def _config_summary() -> list:
    """The MMLSPARK_TPU_* configuration registry as a reference table.

    Every module that declares config variables is imported first, so the
    registry is fully populated regardless of what the caller already
    loaded (the registry is fed at import time, one declaration each).
    """
    import importlib

    from mmlspark_tpu import config
    for mod in ("mmlspark_tpu.observe.costmodel",
                "mmlspark_tpu.observe.history",
                "mmlspark_tpu.parallel.prefetch",
                "mmlspark_tpu.data.autotune",
                "mmlspark_tpu.data.service",
                "mmlspark_tpu.io.remote",
                "mmlspark_tpu.resilience.retry",
                "mmlspark_tpu.resilience.breaker",
                "mmlspark_tpu.resilience.chaos",
                "mmlspark_tpu.resilience.checkpoints"):
        importlib.import_module(mod)
    lines = ["## Configuration registry (`mmlspark_tpu.config`)", "",
             "Every `MMLSPARK_TPU_*` environment variable, declared once "
             "with its default and doc (`config.describe()` at runtime; "
             "precedence: `config.set()` override > environment > "
             "default).", "",
             "| variable | default | doc |", "|---|---|---|"]
    for var in config.describe():
        if not var["declared_by"].startswith("mmlspark_tpu"):
            continue  # test/application declarations made in-process
        doc = " ".join(str(var["doc"]).split())
        lines.append(f"| `{var['name']}` | `{var['default']!r}` | {doc} |")
    lines.append("")
    return lines
