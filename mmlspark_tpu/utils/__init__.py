"""Cross-cutting utilities: stage registry, random data generation."""

from mmlspark_tpu.utils.registry import all_stage_classes, api_summary
from mmlspark_tpu.utils.datagen import ColumnOptions, generate_table

__all__ = ["all_stage_classes", "api_summary", "generate_table",
           "ColumnOptions"]
