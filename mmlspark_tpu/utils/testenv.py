"""The ONE definition of the virtual CPU test mesh environment.

tests/conftest.py, scripts/regen_benchmarks.py, and scripts/regen_examples.py
must all compute on byte-identical backends or the committed pins (grid CSV,
example metrics) silently diverge from what CI verifies.  Call BEFORE jax
creates a backend (env vars alone are too late when sitecustomize imports
jax at interpreter startup — the jax.config updates handle that)."""

from __future__ import annotations

import os

VIRTUAL_DEVICES = 8


def pin_virtual_cpu_mesh() -> None:
    """Force the 8-virtual-device float32 CPU mesh (the local[*] analogue,
    reference SparkSessionFactory.scala:40-51)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_ENABLE_X64"] = "0"
    # FORCE the device count: a leftover foreign
    # --xla_force_host_platform_device_count (e.g. from multihost-worker
    # experiments) must not leak into pin regeneration
    kept = [f for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(f"--xla_force_host_platform_device_count={VIRTUAL_DEVICES}")
    os.environ["XLA_FLAGS"] = " ".join(kept)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", False)
