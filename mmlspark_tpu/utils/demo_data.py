"""Deterministic synthetic datasets for the example/e2e layer.

The reference pins a datasets bundle (tools/config.sh:101-105: Adult Census,
flight delays, Amazon book reviews, CIFAR-10) that its notebooks and the
VerifyTrainClassifier metric grid consume.  This build runs air-gapped, so
the example workloads use generators that reproduce each dataset's *shape*
(mixed types, text, images, class structure) deterministically from a seed —
the workload code paths exercised are identical.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.table import DataTable, object_column


def adult_census_like(n: int = 600, seed: int = 0) -> DataTable:
    """Mixed-type tabular data shaped like Adult Census Income (notebook
    101): numeric, categorical-string, and free-string columns with a
    binary income label correlated to several of them."""
    rng = np.random.default_rng(seed)
    age = rng.integers(17, 80, n).astype(np.float64)
    hours = rng.integers(10, 70, n).astype(np.float64)
    education = rng.choice(
        ["HS-grad", "Bachelors", "Masters", "Doctorate", "Some-college"],
        n, p=[0.35, 0.3, 0.18, 0.05, 0.12])
    workclass = rng.choice(["Private", "Self-emp", "Government"], n,
                           p=[0.7, 0.15, 0.15])
    edu_rank = np.array([{"HS-grad": 0, "Some-college": 1, "Bachelors": 2,
                          "Masters": 3, "Doctorate": 4}[e] for e in education])
    score = (0.05 * (age - 38) + 0.06 * (hours - 40) + 0.9 * edu_rank
             + 0.5 * (workclass == "Self-emp") + rng.normal(0, 1.2, n))
    income = np.where(score > 1.8, ">50K", "<=50K")
    occupation = np.array(
        [f"{w.lower()} {e.lower().replace('-', ' ')} worker"
         for w, e in zip(workclass, education)], object)
    return DataTable({
        "age": age, "hours_per_week": hours,
        "education": object_column(list(education)),
        "workclass": object_column(list(workclass)),
        "occupation": object_column(list(occupation)),
        "income": object_column(list(income)),
    })


def flight_delays_like(n: int = 800, seed: int = 1) -> DataTable:
    """Regression data shaped like the flight-delay dataset (notebook 102):
    numeric + categorical features, continuous delay target."""
    rng = np.random.default_rng(seed)
    day_of_week = rng.integers(1, 8, n).astype(np.float64)
    dep_hour = rng.integers(5, 23, n).astype(np.float64)
    distance = rng.uniform(100, 2500, n)
    carrier = rng.choice(["AA", "DL", "UA", "WN"], n)
    carrier_bias = np.array([{"AA": 4.0, "DL": -2.0, "UA": 6.0,
                              "WN": 0.0}[c] for c in carrier])
    delay = (3.0 * (dep_hour - 12).clip(0) + 0.004 * distance
             + 5.0 * (day_of_week >= 6) + carrier_bias
             + rng.normal(0, 6.0, n))
    return DataTable({
        "day_of_week": day_of_week, "dep_hour": dep_hour,
        "distance": distance, "carrier": object_column(list(carrier)),
        "arr_delay": delay,
    })


_POSITIVE = ["great", "wonderful", "excellent", "loved", "fantastic",
             "brilliant", "superb", "delightful", "rich", "moving"]
_NEGATIVE = ["terrible", "boring", "awful", "hated", "dull", "weak",
             "disappointing", "flat", "tedious", "poor"]
_NEUTRAL = ["book", "story", "author", "chapter", "plot", "characters",
            "writing", "pages", "read", "novel", "the", "a", "was", "felt",
            "this", "it"]


def book_reviews_like(n: int = 400, seed: int = 2) -> DataTable:
    """Text classification data shaped like Amazon book reviews (notebooks
    201/202): free-text reviews with a binary sentiment rating."""
    rng = np.random.default_rng(seed)
    texts, ratings = [], []
    for _ in range(n):
        positive = bool(rng.integers(0, 2))
        pool = _POSITIVE if positive else _NEGATIVE
        n_sent = int(rng.integers(2, 5))
        n_neut = int(rng.integers(6, 14))
        words = list(rng.choice(pool, n_sent)) + \
            list(rng.choice(_NEUTRAL, n_neut))
        rng.shuffle(words)
        texts.append(" ".join(words))
        ratings.append(5 if positive else 1)
    return DataTable({"text": object_column(texts),
                      "rating": np.asarray(ratings, np.float64)})


def cifar_like(n: int = 256, seed: int = 3,
               n_classes: int = 10) -> DataTable:
    """Image classification data shaped like CIFAR-10 (notebook 301):
    32x32x3 uint8 images whose class controls a per-class color/frequency
    pattern, so a small ConvNet can actually learn them."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, n)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    images = np.empty((n, 32, 32, 3), np.uint8)
    for i, cls in enumerate(y):
        phase = 2 * np.pi * cls / n_classes
        freq = 1 + (cls % 3)
        base = np.stack([
            127 + 100 * np.sin(freq * xx / 5 + phase),
            127 + 100 * np.cos(freq * yy / 5 + phase),
            127 + 60 * np.sin((xx + yy) / 7 + phase),
        ], axis=-1)
        noise = rng.normal(0, 25, (32, 32, 3))
        images[i] = np.clip(base + noise, 0, 255).astype(np.uint8)
    return DataTable({"image": images,
                      "label": y.astype(np.float64)})


# --------------------------------------------------------------------------
# the learner-grid datasets (the VerifyTrainClassifier benchmark CSV's
# 9 bundled CSVs, benchmarkMetrics.csv:1-46)
# --------------------------------------------------------------------------

def _blobs(n, d, n_classes, spread, noise, seed):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, spread, size=(n_classes, d))
    y = rng.integers(0, n_classes, n)
    x = centers[y] + rng.normal(0, noise, size=(n, d))
    return x.astype(np.float64), y


def grid_datasets() -> dict[str, DataTable]:
    """Deterministic datasets spanning the difficulty range of the
    reference's benchmark CSVs (easy/separable -> noisy/nonlinear ->
    mixed-type)."""
    out: dict[str, DataTable] = {}

    x, y = _blobs(300, 4, 2, spread=4.0, noise=0.6, seed=10)
    out["blobs_easy"] = DataTable(
        {**{f"f{i}": x[:, i] for i in range(4)}, "label": y.astype(np.float64)})

    x, y = _blobs(300, 6, 2, spread=1.5, noise=1.2, seed=11)
    out["blobs_noisy"] = DataTable(
        {**{f"f{i}": x[:, i] for i in range(6)}, "label": y.astype(np.float64)})

    rng = np.random.default_rng(12)
    x = rng.uniform(-2, 2, size=(300, 2))
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)  # XOR: nonlinear
    out["xor"] = DataTable({"f0": x[:, 0], "f1": x[:, 1],
                            "label": y.astype(np.float64)})

    x, y = _blobs(360, 5, 3, spread=3.5, noise=0.8, seed=13)
    out["blobs_3class"] = DataTable(
        {**{f"f{i}": x[:, i] for i in range(5)}, "label": y.astype(np.float64)})

    out["census_mixed"] = adult_census_like(n=400, seed=14)

    # adversarial shapes, matching the reference grid's breadth (9 CSVs,
    # benchmarkMetrics.csv:1-46) — each targets a failure mode a learner
    # family has actually hit:

    # class imbalance (~6% positives): accuracy alone is a trap; AUC matters
    rng = np.random.default_rng(15)
    n = 400
    y = (rng.random(n) < 0.06).astype(np.int64)
    x = rng.normal(0, 1.0, size=(n, 4)) + 1.6 * y[:, None]
    out["imbalanced"] = DataTable(
        {**{f"f{i}": x[:, i] for i in range(4)}, "label": y.astype(np.float64)})

    # many classes (8) with few rows per class: per-class statistics thin out
    x, y = _blobs(480, 6, 8, spread=5.0, noise=0.9, seed=16)
    out["many_class"] = DataTable(
        {**{f"f{i}": x[:, i] for i in range(6)}, "label": y.astype(np.float64)})

    # collinear features: duplicated/linearly-dependent columns (the exact
    # failure that broke LinearRegression in example 102 — normal equations
    # blow up without the augmented-lstsq fit)
    rng = np.random.default_rng(17)
    base = rng.normal(0, 1, size=(300, 2))
    x = np.column_stack([base[:, 0], base[:, 1],
                         base[:, 0] * 2.0,                  # exact duplicate
                         base[:, 0] + base[:, 1],           # exact sum
                         base[:, 0] + rng.normal(0, 1e-6, 300)])  # near-dup
    y = (base[:, 0] - base[:, 1] > 0).astype(np.int64)
    out["collinear"] = DataTable(
        {**{f"f{i}": x[:, i] for i in range(5)}, "label": y.astype(np.float64)})

    # wide sparse one-hot-ish features (hashed-text regime): d >> informative
    rng = np.random.default_rng(18)
    n, d = 300, 64
    x = (rng.random((n, d)) < 0.05).astype(np.float64)  # ~5% density
    w = np.zeros(d)
    w[:6] = [2.0, -2.0, 1.5, -1.5, 1.0, -1.0]
    y = ((x @ w + rng.normal(0, 0.4, n)) > 0).astype(np.int64)
    out["wide_sparse"] = DataTable(
        {**{f"f{i}": x[:, i] for i in range(d)}, "label": y.astype(np.float64)})

    return out


def digits_images(seed: int = 0):
    """REAL image-classification data: the UCI handwritten-digits set that
    ships inside scikit-learn (1797 8x8 grayscale images, 10 classes) — the
    one genuine labeled image dataset available to an air-gapped build
    (CIFAR-10's raw archive needs network egress; see docs/design_cuts.md).
    Images are nearest-neighbor upscaled to the ConvNetCIFAR10 input
    contract (32, 32, 3) uint8 so the flagship scoring model trains and
    scores on real data with real accuracy semantics (the reference's
    equivalent fixture is its pretrained ConvNet_CIFAR10.model,
    CNTKTestUtils.scala:12-36).

    Returns (x_train, y_train, x_test, y_test): deterministic shuffled
    80/20 split, images uint8 (N, 32, 32, 3), labels int32."""
    from sklearn.datasets import load_digits

    d = load_digits()
    imgs = d.images.astype(np.float32)                 # (N, 8, 8), 0..16
    x = np.kron(imgs, np.ones((1, 4, 4), np.float32))  # -> (N, 32, 32)
    x = np.clip(x * (255.0 / 16.0), 0, 255).astype(np.uint8)
    x = np.repeat(x[..., None], 3, axis=-1)
    y = d.target.astype(np.int32)
    order = np.random.default_rng(seed).permutation(len(x))
    x, y = x[order], y[order]
    n_test = len(x) // 5
    return x[n_test:], y[n_test:], x[:n_test], y[:n_test]
