"""Native library build + load: the NativeLoader equivalent.

The reference extracts prebuilt .so files from jar resources per executor
(NativeLoader.java:29-159, loaded per partition via
ImageSchema.loadLibraryForAllPartitions).  Here the C++ decoder ships as
source and is compiled once per host into a cache directory, then loaded
with ctypes — one process-wide load, no per-partition dance.  If the
toolchain or codec libraries are missing, callers fall back to PIL
(io/image_reader.py), so the framework degrades instead of failing.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SOURCES = ["decode.cpp", "text.cpp"]
_LINK_LIBS = ["-ljpeg", "-lpng", "-lz"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _cache_dir() -> str:
    from mmlspark_tpu import config
    d = config.NATIVE_CACHE.current() or os.path.join(
        os.path.expanduser("~"), ".cache", "mmlspark_tpu", "native")
    os.makedirs(d, exist_ok=True)
    return d


def _source_digest() -> str:
    h = hashlib.sha256()
    for src in _SOURCES:
        with open(os.path.join(_NATIVE_DIR, src), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def build_native() -> str:
    """Compile the decoder if needed; returns the .so path."""
    so_path = os.path.join(_cache_dir(), f"libmmldecode-{_source_digest()}.so")
    if os.path.exists(so_path):
        return so_path
    srcs = [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
    tmp = f"{so_path}.{os.getpid()}.tmp"  # per-process: concurrent builds race
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", "-o", tmp,
           *srcs, *_LINK_LIBS]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)
    return so_path


def get_native_lib() -> Optional[ctypes.CDLL]:
    """The loaded decoder library, or None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            lib = ctypes.CDLL(build_native())
            lib.image_dims.restype = ctypes.c_int
            lib.image_dims.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib.decode_image.restype = ctypes.c_int
            lib.decode_image.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int, ctypes.c_int]
            lib.decode_batch.restype = ctypes.c_int
            lib.decode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int)]
            lib.text_hash_count.restype = ctypes.c_int
            lib.text_hash_count.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_int, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte))]
            lib.text_hash_free.restype = None
            lib.text_hash_free.argtypes = [
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_ubyte)]
            _lib = lib
        except Exception:
            _load_failed = True
    return _lib


def native_decode(data: bytes) -> Optional[np.ndarray]:
    """Decode JPEG/PNG bytes to an (H, W, C) BGR/gray uint8 array.

    Returns None when the buffer is not decodable (the reference's decode
    returns Option, ImageReader.scala:25-40) or the native lib is absent.
    """
    lib = get_native_lib()
    if lib is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    if lib.image_dims(data, len(data), ctypes.byref(w), ctypes.byref(h),
                      ctypes.byref(c)) != 0:
        return None
    out = np.empty((h.value, w.value, c.value), np.uint8)
    rc = lib.decode_image(data, len(data),
                          out.ctypes.data_as(ctypes.c_void_p),
                          w.value, h.value, c.value)
    if rc != 0:
        return None
    return out


def native_decode_batch(buffers: list) -> Optional[list]:
    """Decode a batch of JPEG/PNG byte buffers in parallel C++ threads.

    libjpeg/libpng handles are per-call, so the batch is embarrassingly
    parallel; one ctypes call decodes the whole batch with the GIL held
    once (the data-loader hot path for the streaming reader).  Returns a
    list of (H, W, C) uint8 arrays with None for undecodable entries, or
    None when the native lib is absent (callers fall back per-item).
    """
    lib = get_native_lib()
    if lib is None:
        return None
    n = len(buffers)
    if n == 0:
        return []
    results: list = [None] * n
    idx: list[int] = []
    dims: list[tuple[int, int, int]] = []
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    for i, data in enumerate(buffers):
        if lib.image_dims(data, len(data), ctypes.byref(w), ctypes.byref(h),
                          ctypes.byref(c)) == 0:
            idx.append(i)
            dims.append((w.value, h.value, c.value))
    if not idx:
        return results
    m = len(idx)
    outs = [np.empty((hh, ww, cc), np.uint8) for (ww, hh, cc) in dims]
    buf_arr = (ctypes.c_char_p * m)(*[buffers[i] for i in idx])
    len_arr = (ctypes.c_int64 * m)(*[len(buffers[i]) for i in idx])
    out_arr = (ctypes.c_void_p * m)(
        *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs])
    w_arr = (ctypes.c_int * m)(*[d[0] for d in dims])
    h_arr = (ctypes.c_int * m)(*[d[1] for d in dims])
    c_arr = (ctypes.c_int * m)(*[d[2] for d in dims])
    status = (ctypes.c_int * m)()
    n_threads = min(m, os.cpu_count() or 1, 16)
    lib.decode_batch(buf_arr, len_arr, out_arr, w_arr, h_arr, c_arr,
                     m, n_threads, status)
    for j, i in enumerate(idx):
        if status[j] == 0:
            results[i] = outs[j]
    return results


def native_text_hash(docs: list, stopwords: list, lowercase: bool,
                     lower_for_stop: bool, min_token_len: int,
                     num_features: int, binary: bool) -> Optional[tuple]:
    """Fused tokenize->stop->hash->count over raw document strings.

    Returns (rows, fallback_idx): `rows[i]` is the (slot_ids int32, vals
    float32) sparse row for doc i (None where i is in fallback_idx —
    non-ASCII documents the caller recomputes through the Python stages,
    which own the unicode tables), or None entirely when the native lib
    is absent.  `None` cells tokenize to [] (the Tokenizer contract).
    """
    lib = get_native_lib()
    if lib is None or num_features > 2**31 - 1:
        return None
    enc = [("" if d is None else str(d)).encode("utf-8") for d in docs]
    buf = b"".join(enc)
    offsets = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=offsets[1:])
    senc = [s.encode("utf-8") for s in stopwords]
    sbuf = b"".join(senc)
    soff = np.zeros(len(senc) + 1, np.int64)
    np.cumsum([len(e) for e in senc], out=soff[1:])

    slots_p = ctypes.POINTER(ctypes.c_int)()
    vals_p = ctypes.POINTER(ctypes.c_float)()
    bounds_p = ctypes.POINTER(ctypes.c_int64)()
    status_p = ctypes.POINTER(ctypes.c_ubyte)()
    rc = lib.text_hash_count(
        buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(enc),
        sbuf, soff.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(senc),
        int(lowercase), int(lower_for_stop), int(min_token_len),
        int(num_features), int(binary),
        ctypes.byref(slots_p), ctypes.byref(vals_p), ctypes.byref(bounds_p),
        ctypes.byref(status_p))
    if rc != 0:
        return None
    try:
        n = len(enc)
        bounds = np.ctypeslib.as_array(bounds_p, shape=(n + 1,)).copy()
        total = int(bounds[-1])
        slots = (np.ctypeslib.as_array(slots_p, shape=(total,)).copy()
                 if total else np.zeros(0, np.int32))
        vals = (np.ctypeslib.as_array(vals_p, shape=(total,)).copy()
                if total else np.zeros(0, np.float32))
        status = np.ctypeslib.as_array(status_p, shape=(n,)).copy() \
            if n else np.zeros(0, np.uint8)
    finally:
        lib.text_hash_free(slots_p, vals_p, bounds_p, status_p)
    rows: list = []
    fallback = np.nonzero(status)[0].tolist()
    fb = set(fallback)
    for i in range(n):
        if i in fb:
            rows.append(None)
        else:
            # plain slices: slots/vals are already int32/float32 copies we
            # own, so per-row astype would just duplicate the hot path's
            # output again
            rows.append((slots[bounds[i]:bounds[i + 1]],
                         vals[bounds[i]:bounds[i + 1]]))
    return rows, fallback
