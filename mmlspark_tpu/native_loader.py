"""Native library build + load: the NativeLoader equivalent.

The reference extracts prebuilt .so files from jar resources per executor
(NativeLoader.java:29-159, loaded per partition via
ImageSchema.loadLibraryForAllPartitions).  Here the C++ decoder ships as
source and is compiled once per host into a cache directory, then loaded
with ctypes — one process-wide load, no per-partition dance.  If the
toolchain or codec libraries are missing, callers fall back to PIL
(io/image_reader.py), so the framework degrades instead of failing.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SOURCES = ["decode.cpp"]
_LINK_LIBS = ["-ljpeg", "-lpng"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _cache_dir() -> str:
    from mmlspark_tpu import config
    d = config.NATIVE_CACHE.current() or os.path.join(
        os.path.expanduser("~"), ".cache", "mmlspark_tpu", "native")
    os.makedirs(d, exist_ok=True)
    return d


def _source_digest() -> str:
    h = hashlib.sha256()
    for src in _SOURCES:
        with open(os.path.join(_NATIVE_DIR, src), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def build_native() -> str:
    """Compile the decoder if needed; returns the .so path."""
    so_path = os.path.join(_cache_dir(), f"libmmldecode-{_source_digest()}.so")
    if os.path.exists(so_path):
        return so_path
    srcs = [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
    tmp = f"{so_path}.{os.getpid()}.tmp"  # per-process: concurrent builds race
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, *srcs, *_LINK_LIBS]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)
    return so_path


def get_native_lib() -> Optional[ctypes.CDLL]:
    """The loaded decoder library, or None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            lib = ctypes.CDLL(build_native())
            lib.image_dims.restype = ctypes.c_int
            lib.image_dims.argtypes = [
                ctypes.c_char_p, ctypes.c_long,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib.decode_image.restype = ctypes.c_int
            lib.decode_image.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int, ctypes.c_int]
            _lib = lib
        except Exception:
            _load_failed = True
    return _lib


def native_decode(data: bytes) -> Optional[np.ndarray]:
    """Decode JPEG/PNG bytes to an (H, W, C) BGR/gray uint8 array.

    Returns None when the buffer is not decodable (the reference's decode
    returns Option, ImageReader.scala:25-40) or the native lib is absent.
    """
    lib = get_native_lib()
    if lib is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    if lib.image_dims(data, len(data), ctypes.byref(w), ctypes.byref(h),
                      ctypes.byref(c)) != 0:
        return None
    out = np.empty((h.value, w.value, c.value), np.uint8)
    rc = lib.decode_image(data, len(data),
                          out.ctypes.data_as(ctypes.c_void_p),
                          w.value, h.value, c.value)
    if rc != 0:
        return None
    return out
