"""Word2Vec: skip-gram embeddings with negative sampling.

Counterpart of Spark's Word2Vec as used by the reference's notebook 202
(`notebooks/samples/202 - Amazon Book Reviews - Word2Vec.ipynb`): fit token
embeddings on a corpus, then represent each document as the mean of its
word vectors (Spark's Word2VecModel.transform semantics).

TPU-first design: pair generation (center/context windows, unigram^0.75
negative table) is one vectorized host pass; training is a single jitted
optax step over embedding lookups — all batches have one static shape, so
XLA compiles once and the MXU sees only gathers + batched dot products.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Estimator, Transformer
from mmlspark_tpu.core.table import DataTable


class Word2VecModel(Transformer):
    """Document vectors = mean of fitted word vectors (Spark semantics)."""

    inputCol = Param(None, "token-list column", ptype=str, required=True)
    outputCol = Param("w2v", "document-vector output column", ptype=str)

    def __init__(self, vocab: Optional[list[str]] = None,
                 vectors: Optional[np.ndarray] = None, **kw):
        super().__init__(**kw)
        self._vocab = list(vocab or [])
        self._index = {w: i for i, w in enumerate(self._vocab)}
        self._vectors = (np.asarray(vectors, np.float32)
                         if vectors is not None else None)

    @property
    def vectors(self) -> np.ndarray:
        return self._vectors

    @property
    def vocabulary(self) -> list[str]:
        return list(self._vocab)

    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self._index.get(word)
        return None if i is None else self._vectors[i]

    def find_synonyms(self, word: str, num: int = 5) -> list[tuple[str, float]]:
        """Nearest vocabulary words by cosine similarity (Spark's
        findSynonyms)."""
        v = self.word_vector(word)
        if v is None:
            raise KeyError(f"'{word}' not in the fitted vocabulary")
        norms = np.linalg.norm(self._vectors, axis=1) * np.linalg.norm(v)
        sims = self._vectors @ v / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = [(self._vocab[i], float(sims[i])) for i in order
               if self._vocab[i] != word]
        return out[:num]

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        dim = self._vectors.shape[1]
        docs = np.zeros((table.num_rows, dim), np.float32)
        for r, toks in enumerate(table[self.inputCol]):
            idx = [self._index[t] for t in toks if t in self._index]
            if idx:
                docs[r] = self._vectors[idx].mean(axis=0)
        return table.with_column(self.outputCol, docs)

    def _save_extra(self, path: str) -> None:
        np.save(os.path.join(path, "vectors.npy"), self._vectors)
        with open(os.path.join(path, "vocab.json"), "w") as f:
            json.dump(self._vocab, f)

    def _load_extra(self, path: str) -> None:
        self._vectors = np.load(os.path.join(path, "vectors.npy"))
        with open(os.path.join(path, "vocab.json")) as f:
            self._vocab = json.load(f)
        self._index = {w: i for i, w in enumerate(self._vocab)}


class Word2Vec(Estimator):
    """Fit skip-gram embeddings with negative sampling."""

    inputCol = Param(None, "token-list column", ptype=str, required=True)
    outputCol = Param("w2v", "document-vector output column", ptype=str)
    vectorSize = Param(100, "embedding dimension", ptype=int)
    windowSize = Param(5, "context window radius", ptype=int)
    minCount = Param(5, "minimum token frequency to enter the vocabulary",
                     ptype=int)
    maxIter = Param(1, "passes over the generated pairs", ptype=int)
    stepSize = Param(0.025, "learning rate", ptype=float)
    numNegatives = Param(5, "negative samples per positive pair", ptype=int)
    seed = Param(0, "rng seed", ptype=int)

    def fit(self, table: DataTable) -> Word2VecModel:
        self._check_required()
        docs = [list(t) for t in table[self.inputCol]]
        # vocabulary over minCount (Spark's vocab pruning)
        flat = [t for d in docs for t in d]
        words, counts = np.unique(np.asarray(flat, object), return_counts=True)
        keep = counts >= self.minCount
        vocab = [str(w) for w in words[keep]]
        index = {w: i for i, w in enumerate(vocab)}
        v = len(vocab)
        dim = self.vectorSize
        rng = np.random.default_rng(self.seed)
        if v == 0:
            return Word2VecModel(vocab, np.zeros((0, dim), np.float32),
                                 inputCol=self.inputCol,
                                 outputCol=self.outputCol)

        # one vectorized pass: all (center, context) pairs in all windows
        centers, contexts = [], []
        win = self.windowSize
        for d in docs:
            ids = np.asarray([index[t] for t in d if t in index], np.int32)
            n = len(ids)
            for off in range(1, win + 1):
                if n > off:
                    centers.append(ids[:-off]); contexts.append(ids[off:])
                    centers.append(ids[off:]);  contexts.append(ids[:-off])
        if not centers:
            return Word2VecModel(vocab, np.zeros((v, dim), np.float32),
                                 inputCol=self.inputCol,
                                 outputCol=self.outputCol)
        centers = np.concatenate(centers)
        contexts = np.concatenate(contexts)

        # unigram^0.75 negative-sampling table
        freq = counts[keep].astype(np.float64) ** 0.75
        neg_p = freq / freq.sum()

        in_vecs = jnp.asarray(
            rng.uniform(-0.5 / dim, 0.5 / dim, (v, dim)).astype(np.float32))
        out_vecs = jnp.zeros((v, dim), jnp.float32)
        params = {"in": in_vecs, "out": out_vecs}
        tx = optax.sgd(self.stepSize)
        opt_state = tx.init(params)
        k_neg = self.numNegatives

        def loss_fn(p, c, o, neg):
            vc = p["in"][c]                      # (B, D)
            uo = p["out"][o]                     # (B, D)
            un = p["out"][neg]                   # (B, K, D)
            pos = jax.nn.log_sigmoid(jnp.sum(vc * uo, -1))
            negs = jax.nn.log_sigmoid(-jnp.einsum("bd,bkd->bk", vc, un))
            return -(pos.mean() + negs.sum(-1).mean())

        @jax.jit
        def step(p, s, c, o, neg):
            l, g = jax.value_and_grad(loss_fn)(p, c, o, neg)
            updates, s = tx.update(g, s, p)
            return optax.apply_updates(p, updates), s, l

        batch = 4096
        n_pairs = len(centers)
        pad = (-n_pairs) % batch
        for _ in range(self.maxIter):
            # wrap-around padding keeps every batch at the one static shape
            # (one XLA compile); negatives are drawn per batch so host
            # memory stays O(batch * k_neg) regardless of corpus size
            order = np.resize(rng.permutation(n_pairs), n_pairs + pad)
            for s0 in range(0, len(order), batch):
                sl = order[s0:s0 + batch]
                negs = rng.choice(v, size=(batch, k_neg),
                                  p=neg_p).astype(np.int32)
                params, opt_state, _ = step(
                    params, opt_state,
                    jnp.asarray(centers[sl]), jnp.asarray(contexts[sl]),
                    jnp.asarray(negs))
        return Word2VecModel(vocab, np.asarray(params["in"]),
                             inputCol=self.inputCol,
                             outputCol=self.outputCol)
