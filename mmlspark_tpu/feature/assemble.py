"""Auto-featurization: AssembleFeatures / Featurize.

TPU-native counterpart of the reference's featurize component
(AssembleFeatures.scala:133-390, Featurize.scala:67-82): per-column type
dispatch, string hashing with count-based slot selection, one-hot encoding
of categoricals, missing-value handling, and assembly — except the output
is a dense float32 matrix column ready for device transfer instead of a
Spark SparseVector, and the per-row UDF loops become batched numpy ops.

Block order mirrors FastVectorAssembler's categoricals-first rule
(FastVectorAssembler.scala:50): categorical blocks, then numeric/vector
blocks in input order, then the hash-selected string block last.  The block
plan is recorded in the output column's metadata so learners can recover
slot names, categorical slot ranges, and the total width (the MLP
input-autosizing information, TrainClassifier.scala:143-150).
"""

from __future__ import annotations

import json
import os
import weakref
from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Estimator, Pipeline, PipelineModel, Transformer
from mmlspark_tpu.core.schema import ColumnMeta
from mmlspark_tpu.core.table import DataTable, object_column
from mmlspark_tpu.feature.hashing import (densify_sparse_column,
                                          hash_token_lists, nonzero_slots)

# 2^18 slots by default; 2^12 for tree/NN learners (Featurize.scala:13-19)
NUM_FEATURES_DEFAULT = 1 << 18
NUM_FEATURES_TREE_OR_NN = 1 << 12


def _tokenize_string_columns(cols_data, n: int) -> list[list[str]]:
    """Per-row token lists over several string columns, one pass per column
    (reference hashStringColumns, AssembleFeatures.scala:46-53)."""
    row_tokens: list[list[str]] = [[] for _ in range(n)]
    for cd in cols_data:
        for i, v in enumerate(cd):
            if v is None or v == "":
                continue
            row_tokens[i].extend(str(v).lower().split())
    return row_tokens


class AssembleFeatures(Estimator):
    """Fit the per-column featurization plan on a table."""

    columnsToFeaturize = Param(None, "columns to featurize",
                               ptype=(list, tuple), required=True)
    featuresCol = Param("features", "assembled output column", ptype=str)
    numberOfFeatures = Param(NUM_FEATURES_DEFAULT,
                             "hash space for string columns", ptype=int)
    oneHotEncodeCategoricals = Param(True, "one-hot encode categoricals",
                                     ptype=bool)

    def fit(self, table: DataTable) -> "AssembleFeaturesModel":
        self._check_required()
        cat_blocks: list[dict] = []
        num_blocks: list[dict] = []
        hash_cols: list[str] = []
        clean_cols: list[str] = []

        for col in self.columnsToFeaturize:
            arr = table[col]
            meta = table.meta(col)
            if meta.is_categorical:
                cat_blocks.append({
                    "col": col, "kind": "categorical",
                    "num_levels": meta.categorical.num_levels,
                    # persist the fitted level order: score-time tables may
                    # carry raw values or a differently-inferred encoding
                    "levels": list(meta.categorical.levels),
                    "ohe": bool(self.oneHotEncodeCategoricals),
                })
                continue
            if arr.dtype == object:
                if any(isinstance(v, str) for v in arr if v is not None):
                    hash_cols.append(col)
                else:  # numeric-list rows must form a rectangular block
                    widths = {len(np.asarray(v).ravel()) for v in arr
                              if v is not None}
                    if len(widths) > 1:
                        raise ValueError(
                            f"column '{col}' has ragged numeric rows "
                            f"(widths {sorted(widths)}); pad or split it "
                            "before featurizing")
                    num_blocks.append({"col": col, "kind": "vector",
                                       "width": widths.pop() if widths else 0})
                    clean_cols.append(col)
                continue
            if arr.ndim > 1:
                num_blocks.append({"col": col, "kind": "vector",
                                   "width": int(np.prod(arr.shape[1:]))})
                clean_cols.append(col)
            elif np.issubdtype(arr.dtype, np.floating):
                num_blocks.append({"col": col, "kind": "numeric", "width": 1})
                clean_cols.append(col)
            elif np.issubdtype(arr.dtype, np.number) or arr.dtype == np.bool_:
                num_blocks.append({"col": col, "kind": "numeric", "width": 1})
            elif np.issubdtype(arr.dtype, np.str_):
                hash_cols.append(col)

        selected = None
        fit_rows = None
        if hash_cols:
            nf = self.numberOfFeatures
            cols_data = [table[c] for c in hash_cols]
            fit_rows = hash_token_lists(
                _tokenize_string_columns(cols_data, table.num_rows), nf)
            selected = nonzero_slots(fit_rows)

        model = AssembleFeaturesModel(
            cat_blocks=cat_blocks, num_blocks=num_blocks,
            hash_cols=hash_cols, clean_cols=clean_cols,
            selected_slots=selected,
            featuresCol=self.featuresCol,
            numberOfFeatures=self.numberOfFeatures,
        )
        if fit_rows is not None:
            # the pipeline transforms the fit table right after fit(); reuse
            # the hashed rows instead of re-tokenizing the whole corpus
            model._fit_cache = (weakref.ref(table), fit_rows)
        return model


class AssembleFeaturesModel(Transformer):
    """Apply the fitted featurization plan
    (reference AssembleFeaturesModel.transform, AssembleFeatures.scala:307-390).

    Rows with missing values in float/vector feature columns are dropped,
    as the reference's na.drop does (line 352).
    """

    featuresCol = Param("features", "assembled output column", ptype=str)
    numberOfFeatures = Param(NUM_FEATURES_DEFAULT, "hash space", ptype=int)

    def __init__(self, cat_blocks: Optional[list] = None,
                 num_blocks: Optional[list] = None,
                 hash_cols: Optional[list] = None,
                 clean_cols: Optional[list] = None,
                 selected_slots: Optional[np.ndarray] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self._cat_blocks = list(cat_blocks or [])
        self._num_blocks = list(num_blocks or [])
        self._hash_cols = list(hash_cols or [])
        self._clean_cols = list(clean_cols or [])
        self._selected = (np.asarray(selected_slots, np.int32)
                          if selected_slots is not None else None)
        self._fit_cache: Optional[tuple] = None

    @property
    def feature_blocks(self) -> list[dict]:
        """The assembled block plan, categoricals first."""
        blocks = []
        for b in self._cat_blocks:
            width = (b["num_levels"] - 1 if b["ohe"] else 1)
            blocks.append({**b, "width": max(width, 0)})
        blocks.extend({**b} for b in self._num_blocks)
        if self._hash_cols:
            blocks.append({"col": "+".join(self._hash_cols), "kind": "hashed",
                           "width": len(self._selected)})
        return blocks

    @property
    def num_output_features(self) -> int:
        return int(sum(b["width"] for b in self.feature_blocks))

    def _categorical_indices(self, table: DataTable, block: dict) -> np.ndarray:
        """Indices in the FITTED level order.

        Score-time tables may hold raw values (strings) or a categorical
        encoding inferred from different data; both are re-mapped through
        the levels saved at fit time (the reference reads them from column
        metadata, Categoricals.scala:186-261).  Unseen values become -1 and
        one-hot to all zeros.
        """
        from mmlspark_tpu.core.schema import CategoricalMap
        arr = table[block["col"]]
        fitted = CategoricalMap(block["levels"])
        own = table.meta(block["col"]).categorical
        if own is not None:
            if list(own.levels) == block["levels"]:
                return np.asarray(arr, np.int64)
            # re-encoded with different levels: decode then re-map
            return fitted.to_indices(list(own.to_levels(arr))).astype(np.int64)
        if arr.dtype == object or np.issubdtype(arr.dtype, np.str_):
            return fitted.to_indices(list(arr)).astype(np.int64)
        # raw numeric values that match the fitted levels
        if set(np.unique(arr).tolist()) <= set(block["levels"]):
            return fitted.to_indices(arr.tolist()).astype(np.int64)
        return np.asarray(arr, np.int64)

    def transform(self, table: DataTable) -> DataTable:
        for col in self._hash_cols:
            if table[col].dtype != object and not np.issubdtype(
                    table[col].dtype, np.str_):
                raise TypeError(
                    f"column '{col}' must be string at score time "
                    "(reference AssembleFeatures.scala:314)")
        kept = table.drop_nulls(self._clean_cols) if self._clean_cols else table
        n = kept.num_rows
        parts: list[np.ndarray] = []

        for b in self._cat_blocks:
            idx = self._categorical_indices(kept, b)
            if b["ohe"]:
                # Spark OneHotEncoder dropLast: last level encodes as zeros
                width = max(b["num_levels"] - 1, 0)
                block = np.zeros((n, width), np.float32)
                ok = (idx >= 0) & (idx < width)
                block[np.arange(n)[ok], idx[ok]] = 1.0
                parts.append(block)
            else:
                parts.append(idx.astype(np.float32)[:, None])

        for b in self._num_blocks:
            arr = kept[b["col"]]
            if arr.dtype == object:
                arr = np.stack([np.asarray(v, np.float32).ravel() for v in arr]) \
                    if n else np.zeros((0, b["width"]), np.float32)
            block = arr.astype(np.float32)
            parts.append(block.reshape(n, -1) if block.ndim != 1
                         else block[:, None])

        if self._hash_cols:
            nf = self.numberOfFeatures
            rows = None
            cache = self._fit_cache
            self._fit_cache = None  # single-shot: free the corpus rows
            if (cache is not None and cache[0]() is table
                    and kept.num_rows == table.num_rows):
                rows = cache[1]
            if rows is None:
                cols_data = [kept[c] for c in self._hash_cols]
                rows = hash_token_lists(
                    _tokenize_string_columns(cols_data, n), nf)
            parts.append(densify_sparse_column(object_column(rows),
                                               selected=self._selected))

        features = (np.concatenate(parts, axis=1) if parts
                    else np.zeros((n, 0), np.float32))
        meta = ColumnMeta()
        meta.extra["feature_blocks"] = [
            {k: v for k, v in b.items()} for b in self.feature_blocks]
        meta.extra["num_features"] = int(features.shape[1])
        return kept.with_column(self.featuresCol, features, meta=meta)

    # -- persistence ----------------------------------------------------
    def _save_extra(self, path: str) -> None:
        plan = {"cat_blocks": self._cat_blocks, "num_blocks": self._num_blocks,
                "hash_cols": self._hash_cols, "clean_cols": self._clean_cols}
        with open(os.path.join(path, "plan.json"), "w") as f:
            json.dump(plan, f)
        if self._selected is not None:
            np.save(os.path.join(path, "selected.npy"), self._selected)

    def _load_extra(self, path: str) -> None:
        with open(os.path.join(path, "plan.json")) as f:
            plan = json.load(f)
        self._cat_blocks = plan["cat_blocks"]
        self._num_blocks = plan["num_blocks"]
        self._hash_cols = plan["hash_cols"]
        self._clean_cols = plan["clean_cols"]
        sel = os.path.join(path, "selected.npy")
        self._selected = np.load(sel) if os.path.exists(sel) else None


class Featurize(Estimator):
    """Featurize several column groups, one AssembleFeatures per output
    (reference Featurize.scala:67-82: featureColumns map -> Pipeline)."""

    featureColumns = Param(None, "output col -> list of input cols",
                           ptype=dict, required=True)
    numberOfFeatures = Param(NUM_FEATURES_DEFAULT, "hash space", ptype=int)
    oneHotEncodeCategoricals = Param(True, "one-hot encode categoricals",
                                     ptype=bool)

    def fit(self, table: DataTable) -> PipelineModel:
        self._check_required()
        stages = [
            AssembleFeatures(
                columnsToFeaturize=list(cols), featuresCol=out,
                numberOfFeatures=self.numberOfFeatures,
                oneHotEncodeCategoricals=self.oneHotEncodeCategoricals)
            for out, cols in self.featureColumns.items()
        ]
        return Pipeline(stages).fit(table)
