"""Featurization layer (reference L4: featurize/, text-featurizer/)."""

from mmlspark_tpu.feature.assemble import AssembleFeatures, AssembleFeaturesModel, Featurize
from mmlspark_tpu.feature.text import (
    HashingTF,
    IDF,
    IDFModel,
    NGram,
    StopWordsRemover,
    TextFeaturizer,
    Tokenizer,
)
from mmlspark_tpu.feature.hashing import densify_sparse_column, stable_hash
from mmlspark_tpu.feature.word2vec import Word2Vec, Word2VecModel

__all__ = [
    "AssembleFeatures", "AssembleFeaturesModel", "Featurize",
    "Tokenizer", "StopWordsRemover", "NGram", "HashingTF", "IDF", "IDFModel",
    "TextFeaturizer", "stable_hash", "densify_sparse_column",
    "Word2Vec", "Word2VecModel",
]
