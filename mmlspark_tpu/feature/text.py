"""Configurable text featurization chain.

TPU-native counterpart of the reference's text-featurizer
(TextFeaturizer.scala:18-290): RegexTokenizer → StopWordsRemover → NGram →
HashingTF → IDF, each stage optional and chained by param rewiring.  Every
stage is an independent pipeline Transformer/Estimator here too, so they
compose outside TextFeaturizer as well.

Token columns are object columns of python string lists; hashed output is a
sparse-row object column (see feature/hashing.py) carrying
`num_features`/`binary` in column metadata.
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import (Estimator, PipelineModel, Transformer)
from mmlspark_tpu.core.table import DataTable, object_column as _object_column
from mmlspark_tpu.feature.hashing import concat_sparse_rows, hash_token_lists

# A standard English stop-word list (the usual Porter/SMART subset Spark's
# loadDefaultStopWords("english") ships; reference TextFeaturizer.scala:245-253).
ENGLISH_STOP_WORDS = frozenset("""
a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could
couldn't did didn't do does doesn't doing don't down during each few for from
further had hadn't has hasn't have haven't having he he'd he'll he's her here
here's hers herself him himself his how how's i i'd i'll i'm i've if in into
is isn't it it's its itself let's me more most mustn't my myself no nor not of
off on once only or other ought our ours ourselves out over own same shan't
she she'd she'll she's should shouldn't so some such than that that's the
their theirs them themselves then there there's these they they'd they'll
they're they've this those through to too under until up very was wasn't we
we'd we'll we're we've were weren't what what's when when's where where's
which while who who's whom why why's with won't would wouldn't you you'd
you'll you're you've your yours yourself yourselves
""".split())


class Tokenizer(Transformer):
    """Regex tokenizer (reference wraps Spark's RegexTokenizer,
    TextFeaturizer.scala:240-245: gaps/pattern/minTokenLength/toLowercase)."""

    inputCol = Param(None, "string column to tokenize", ptype=str, required=True)
    outputCol = Param("tokens", "token-list output column", ptype=str)
    pattern = Param(r"\s+", "regex: split pattern when gaps, match pattern "
                    "otherwise", ptype=str)
    gaps = Param(True, "pattern matches gaps (split) vs tokens (findall)",
                 ptype=bool)
    minTokenLength = Param(0, "drop tokens shorter than this", ptype=int)
    toLowercase = Param(True, "lowercase before tokenizing", ptype=bool)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        rx = re.compile(self.pattern)
        min_len = self.minTokenLength
        lower = self.toLowercase
        gaps = self.gaps

        def tok(text) -> list[str]:
            if text is None:
                return []
            s = str(text)
            if lower:
                s = s.lower()
            parts = rx.split(s) if gaps else rx.findall(s)
            return [t for t in parts if len(t) >= min_len and t]

        tokens = [tok(v) for v in table[self.inputCol]]
        return table.with_column(self.outputCol, _object_column(tokens))


class StopWordsRemover(Transformer):
    """Filter stop words from token lists (reference TextFeaturizer.scala:246-253)."""

    inputCol = Param(None, "token-list column", ptype=str, required=True)
    outputCol = Param("filtered", "output column", ptype=str)
    stopWords = Param(None, "custom stop words (None = default English list)",
                      ptype=(list, tuple))
    caseSensitive = Param(False, "case-sensitive matching", ptype=bool)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        words = self.stopWords
        cs = self.caseSensitive
        stop = (set(words) if words is not None else set(ENGLISH_STOP_WORDS))
        if not cs:
            stop = {w.lower() for w in stop}
        out = [[t for t in toks if (t if cs else t.lower()) not in stop]
               for toks in table[self.inputCol]]
        return table.with_column(self.outputCol, _object_column(out))


class NGram(Transformer):
    """Enumerate word n-grams (reference TextFeaturizer.scala:255-256)."""

    inputCol = Param(None, "token-list column", ptype=str, required=True)
    outputCol = Param("ngrams", "output column", ptype=str)
    n = Param(2, "gram size", ptype=int, validator=lambda v: v >= 1)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        n = self.n
        out = [[" ".join(toks[i:i + n]) for i in range(len(toks) - n + 1)]
               for toks in table[self.inputCol]]
        return table.with_column(self.outputCol, _object_column(out))


class HashingTF(Transformer):
    """Hash token lists into term-count sparse rows
    (reference TextFeaturizer.scala:257-259; Spark default 2^18 slots)."""

    inputCol = Param(None, "token-list column", ptype=str, required=True)
    outputCol = Param("tf", "sparse term-count output column", ptype=str)
    numFeatures = Param(1 << 18, "hash space size", ptype=int,
                        validator=lambda v: v > 0)
    binary = Param(False, "binary presence instead of counts", ptype=bool)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        nf, binary = self.numFeatures, self.binary
        rows = hash_token_lists(list(table[self.inputCol]), nf, binary)
        out = table.with_column(self.outputCol, _object_column(rows))
        meta = out.meta(self.outputCol)
        meta.extra.update(num_features=nf, sparse=True)
        out.set_meta(self.outputCol, meta)
        return out


class IDFModel(Transformer):
    """Apply fitted inverse-document-frequency weights to sparse rows."""

    inputCol = Param(None, "sparse term-count column", ptype=str, required=True)
    outputCol = Param("tfidf", "output column", ptype=str)

    def __init__(self, idf: Optional[dict[int, float]] = None,
                 default_weight: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self._idf = dict(idf or {})
        # weight for slots unseen at fit time: log(n+1) per the df=0 case of
        # Spark's formula when minDocFreq permits, else 0
        self._default = float(default_weight)

    def transform(self, table: DataTable) -> DataTable:
        self._check_required()
        col = table[self.inputCol]
        # one vectorized weight lookup over the concatenated corpus
        slots = np.fromiter(self._idf.keys(), np.int64, len(self._idf))
        order = np.argsort(slots)
        slots = slots[order]
        weights = np.fromiter(self._idf.values(), np.float32,
                              len(self._idf))[order]
        row_ids, indices, values = concat_sparse_rows(col)
        w = np.full(len(indices), self._default, np.float32)
        if len(slots) and len(indices):
            pos = np.searchsorted(slots, indices)
            ok = ((pos < len(slots))
                  & (slots[np.minimum(pos, len(slots) - 1)] == indices))
            w[ok] = weights[pos[ok]]
        weighted = values * w
        bounds = np.searchsorted(row_ids, np.arange(len(col) + 1))
        rows = [(col[i][0], weighted[bounds[i]:bounds[i + 1]])
                for i in range(len(col))]
        out = table.with_column(self.outputCol, _object_column(rows))
        meta = table.meta(self.inputCol).copy()
        out.set_meta(self.outputCol, meta)
        return out

    def _save_extra(self, path: str) -> None:
        import json, os
        with open(os.path.join(path, "idf.json"), "w") as f:
            json.dump({"weights": {str(k): v for k, v in self._idf.items()},
                       "default": self._default}, f)

    def _load_extra(self, path: str) -> None:
        import json, os
        with open(os.path.join(path, "idf.json")) as f:
            d = json.load(f)
        self._idf = {int(k): float(v) for k, v in d["weights"].items()}
        self._default = float(d.get("default", 0.0))


class IDF(Estimator):
    """Fit IDF weights: log((n+1)/(df+1)), Spark's formula, with minDocFreq
    zeroing rare terms (reference TextFeaturizer.scala:260-261)."""

    inputCol = Param(None, "sparse term-count column", ptype=str, required=True)
    outputCol = Param("tfidf", "output column", ptype=str)
    minDocFreq = Param(0, "terms in fewer docs get zero weight", ptype=int)

    def fit(self, table: DataTable) -> IDFModel:
        self._check_required()
        col = table[self.inputCol]
        # indices are unique within a row, so corpus-wide slot counts ARE
        # document frequencies — one np.unique over the concatenation
        _, indices, _ = concat_sparse_rows(col)
        slots, counts = np.unique(indices, return_counts=True)
        n = len(col)
        min_df = self.minDocFreq
        keep = counts >= min_df
        log_w = np.log((n + 1.0) / (counts[keep] + 1.0))
        idf = {int(s): float(v) for s, v in zip(slots[keep], log_w)}
        default = float(np.log(n + 1.0)) if min_df <= 0 else 0.0
        return IDFModel(idf, default_weight=default,
                        inputCol=self.inputCol, outputCol=self.outputCol)


class TextFeaturizerModel(PipelineModel):
    """Fitted text chain; drops the intermediate token/tf columns
    (reference TextFeaturizerModel, TextFeaturizer.scala:350-367).

    When the chain's prefix is the default shape — Tokenizer(gaps, \\s+)
    [-> StopWordsRemover] -> HashingTF — scoring runs it as ONE fused C++
    sweep over the raw strings (native/text.cpp: no Python token objects
    materialized), byte-identical to the staged path; rows the kernel
    declines (non-ASCII: unicode tables stay in Python) and any remaining
    stages (IDF) run through the ordinary stage path.  The stages remain
    the source of truth for params and persistence."""

    def __init__(self, stages=None, cols_to_drop: Optional[list] = None, **kw):
        super().__init__(stages, **kw)
        self._drop = list(cols_to_drop or [])

    def _fused_prefix(self):
        """(n_stages_fused, kwargs for native_text_hash) or None."""
        stages = self._stages
        if not stages or not isinstance(stages[0], Tokenizer):
            return None
        tok = stages[0]
        if not tok.gaps or tok.pattern != r"\s+":
            return None
        i, stop_words, case_sensitive = 1, [], False
        cur_col = tok.outputCol
        if i < len(stages) and isinstance(stages[i], StopWordsRemover):
            sw = stages[i]
            if sw.inputCol != cur_col:
                return None  # non-linear wiring: fusion would change results
            cur_col = sw.outputCol
            words = (list(sw.stopWords) if sw.stopWords is not None
                     else sorted(ENGLISH_STOP_WORDS))
            case_sensitive = sw.caseSensitive
            stop_words = words if case_sensitive else \
                [w.lower() for w in words]
            if any(ord(c) > 127 for w in stop_words for c in w):
                return None  # non-ASCII stop words: python path only
            i += 1
        if i >= len(stages) or not isinstance(stages[i], HashingTF):
            return None
        tf = stages[i]
        if tf.inputCol != cur_col:
            return None  # stage chain is not a straight line
        return i + 1, dict(
            stopwords=stop_words,
            lowercase=tok.toLowercase,
            # membership tests t.lower() when the remover is
            # case-insensitive but tokens were not already lowercased
            lower_for_stop=(not case_sensitive and not tok.toLowercase),
            min_token_len=tok.minTokenLength,
            num_features=tf.numFeatures,
            binary=tf.binary,
        ), tok.inputCol, tf.outputCol, tf

    def _transform_fused(self, table: DataTable):
        from mmlspark_tpu.native_loader import native_text_hash
        spec = self._fused_prefix()
        if spec is None:
            return None
        n_fused, kwargs, in_col, tf_col, tf_stage = spec
        docs = list(table[in_col])
        result = native_text_hash(docs, **kwargs)
        if result is None:
            return None
        rows, fallback = result
        if fallback:
            # non-ASCII rows: exact recompute through the python stages
            sub = DataTable({in_col: _object_column(
                [docs[i] for i in fallback])})
            for st in self._stages[:n_fused]:
                sub = st.transform(sub)
            for j, i in enumerate(fallback):
                rows[i] = sub[tf_col][j]
        out = table.with_column(tf_col, _object_column(rows))
        meta = out.meta(tf_col)
        meta.extra.update(num_features=tf_stage.numFeatures, sparse=True)
        out.set_meta(tf_col, meta)
        for st in self._stages[n_fused:]:
            out = st.transform(out)
        return out

    def transform(self, table: DataTable) -> DataTable:
        clash = [c for c in self._drop if c in table]
        if clash:
            raise ValueError(
                f"input table already has columns {clash}, which this fitted "
                "model uses as intermediates; rename them before scoring")
        out = self._transform_fused(table)
        if out is None:
            out = super().transform(table)
        return out.drop(*[c for c in self._drop if c in out])

    def _save_extra(self, path: str) -> None:
        import json, os
        super()._save_extra(path)
        with open(os.path.join(path, "drop.json"), "w") as f:
            json.dump(self._drop, f)

    def _load_extra(self, path: str) -> None:
        import json, os
        super()._load_extra(path)
        with open(os.path.join(path, "drop.json")) as f:
            self._drop = json.load(f)


class TextFeaturizer(Estimator):
    """Build and fit the configured text chain (reference fit at
    TextFeaturizer.scala:230-290: optional stages, then param rewiring —
    here the chain is assembled directly)."""

    inputCol = Param(None, "input text (or token-list) column", ptype=str,
                     required=True)
    outputCol = Param("features", "output column", ptype=str)
    useTokenizer = Param(True, "tokenize the input", ptype=bool)
    tokenizerGaps = Param(True, "regex matches gaps", ptype=bool)
    tokenizerPattern = Param(r"\s+", "tokenizer regex", ptype=str)
    minTokenLength = Param(0, "minimum token length", ptype=int)
    toLowercase = Param(True, "lowercase text", ptype=bool)
    useStopWordsRemover = Param(False, "remove stop words", ptype=bool)
    caseSensitiveStopWords = Param(False, "case sensitive stop words", ptype=bool)
    defaultStopWordLanguage = Param("english", "stop word language or 'custom'",
                                    ptype=str)
    stopWords = Param(None, "custom stop words, comma separated", ptype=str)
    useNGram = Param(False, "enumerate n-grams", ptype=bool)
    nGramLength = Param(2, "n-gram size", ptype=int)
    binary = Param(False, "binary term counts", ptype=bool)
    numFeatures = Param(1 << 18, "hash space size", ptype=int)
    useIDF = Param(True, "rescale by inverse document frequency", ptype=bool)
    minDocFreq = Param(1, "minimum document frequency for IDF", ptype=int)

    def fit(self, table: DataTable) -> TextFeaturizerModel:
        self._check_required()
        stages: list = []
        cur = self.inputCol
        drop: list[str] = []

        def next_col(suffix: str) -> str:
            name = table.find_unused_column_name(f"{self.outputCol}_{suffix}")
            drop.append(name)
            return name

        if self.useTokenizer:
            out = next_col("tok")
            stages.append(Tokenizer(
                inputCol=cur, outputCol=out, gaps=self.tokenizerGaps,
                pattern=self.tokenizerPattern,
                minTokenLength=self.minTokenLength,
                toLowercase=self.toLowercase))
            cur = out
        if self.useStopWordsRemover:
            out = next_col("sw")
            custom = ([w.strip() for w in self.stopWords.split(",") if w.strip()]
                      if self.defaultStopWordLanguage == "custom"
                      and self.stopWords else None)
            stages.append(StopWordsRemover(
                inputCol=cur, outputCol=out, stopWords=custom,
                caseSensitive=self.caseSensitiveStopWords))
            cur = out
        if self.useNGram:
            out = next_col("ng")
            stages.append(NGram(inputCol=cur, outputCol=out, n=self.nGramLength))
            cur = out
        tf_out = next_col("tf") if self.useIDF else self.outputCol
        stages.append(HashingTF(inputCol=cur, outputCol=tf_out,
                                numFeatures=self.numFeatures,
                                binary=self.binary))
        fitted: list[Transformer] = []
        current = table
        for st in stages:
            current = st.transform(current)
            fitted.append(st)
        if self.useIDF:
            idf = IDF(inputCol=tf_out, outputCol=self.outputCol,
                      minDocFreq=self.minDocFreq).fit(current)
            fitted.append(idf)
        return TextFeaturizerModel(fitted, cols_to_drop=drop)
