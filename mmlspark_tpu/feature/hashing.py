"""Stable token hashing + sparse-row helpers for the hashing trick.

The reference's string featurization hashes tokens with Spark's HashingTF
into 2^18 slots and keeps only slots seen non-zero in the fit corpus
(AssembleFeatures.scala:198-224: BitSet reduce + VectorSlicer).  Slot
selection is what makes the TPU path dense-friendly: XLA is dense-first, so
instead of materializing 262144-wide batches we select the observed slots
once at fit time and emit a dense (rows, n_selected) block.

Sparse rows (pre-selection) are represented as (indices:int32, values:float32)
tuples in an object column — the host-side analogue of Spark's SparseVector.
"""

from __future__ import annotations

import zlib
from typing import Iterable, Optional, Sequence

import numpy as np


def stable_hash(token: str) -> int:
    """Process-stable 32-bit token hash (crc32; Python's hash() is salted)."""
    return zlib.crc32(token.encode("utf-8"))


def hash_tokens_to_slots(tokens: Iterable[str], num_features: int) -> np.ndarray:
    """Map tokens to slot ids in [0, num_features)."""
    return np.asarray([stable_hash(t) % num_features for t in tokens],
                      dtype=np.int64)


def sparse_count_row(tokens: Sequence[str], num_features: int,
                     binary: bool = False) -> tuple[np.ndarray, np.ndarray]:
    """One row of term counts as (sorted unique indices, counts)."""
    if len(tokens) == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.float32))
    slots = hash_tokens_to_slots(tokens, num_features)
    idx, counts = np.unique(slots, return_counts=True)
    vals = (np.ones(len(idx), np.float32) if binary
            else counts.astype(np.float32))
    return idx.astype(np.int32), vals


def hash_token_lists(token_lists: Sequence[Sequence[str]], num_features: int,
                     binary: bool = False) -> list[tuple[np.ndarray, np.ndarray]]:
    """All rows' term counts in one bulk pass.

    Equivalent to `[sparse_count_row(toks, ...) for toks in token_lists]`
    but hashes the whole corpus in a single C-speed sweep and
    segment-reduces counts with ONE np.unique over (row, slot) keys — the
    reference ran this as a distributed Spark job
    (AssembleFeatures.scala:198-224); per-row Python calls would leave the
    TPU idling behind the host at corpus scale.
    """
    n = len(token_lists)
    lengths = (np.fromiter((len(t) for t in token_lists), np.int64, n)
               if n else np.zeros(0, np.int64))
    total = int(lengths.sum())
    if total == 0:
        empty = (np.zeros(0, np.int32), np.zeros(0, np.float32))
        return [empty] * n
    hashes = np.fromiter(
        (zlib.crc32(t.encode("utf-8")) for toks in token_lists for t in toks),
        np.uint32, total)
    slots = hashes.astype(np.int64) % num_features
    row_ids = np.repeat(np.arange(n, dtype=np.int64), lengths)
    keys = row_ids * num_features + slots
    uniq, counts = np.unique(keys, return_counts=True)
    rows = uniq // num_features
    slot_ids = (uniq % num_features).astype(np.int32)
    vals = (np.ones(len(uniq), np.float32) if binary
            else counts.astype(np.float32))
    bounds = np.searchsorted(rows, np.arange(n + 1))
    return [(slot_ids[bounds[i]:bounds[i + 1]], vals[bounds[i]:bounds[i + 1]])
            for i in range(n)]


def concat_sparse_rows(col) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Flatten a sparse-row column to (row_ids, indices, values)."""
    n = len(col)
    lengths = (np.fromiter((len(idx) for idx, _ in col), np.int64, n)
               if n else np.zeros(0, np.int64))
    if int(lengths.sum()) == 0:
        return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                np.zeros(0, np.float32))
    row_ids = np.repeat(np.arange(n, dtype=np.int64), lengths)
    indices = np.concatenate([np.asarray(idx, np.int64) for idx, _ in col
                              if len(idx)])
    values = np.concatenate([np.asarray(v, np.float32) for idx, v in col
                             if len(idx)])
    return row_ids, indices, values


def nonzero_slots(sparse_rows: Iterable[tuple[np.ndarray, np.ndarray]]) -> np.ndarray:
    """Union of observed slot ids over the corpus (the BitSet reduce)."""
    arrays = [np.asarray(idx, np.int64) for idx, _ in sparse_rows]
    if not arrays:
        return np.zeros(0, np.int32)
    return np.unique(np.concatenate(arrays)).astype(np.int32)


def densify_sparse_column(col: np.ndarray,
                          selected: Optional[np.ndarray] = None,
                          num_features: Optional[int] = None) -> np.ndarray:
    """Materialize sparse rows as a dense float32 matrix.

    With `selected`, emit one dense column per selected slot (the
    VectorSlicer path); otherwise emit the full `num_features` width.
    """
    n = len(col)
    row_ids, indices, values = concat_sparse_rows(col)
    if selected is not None:
        width = len(selected)
        out = np.zeros((n, width), np.float32)
        if width == 0 or len(indices) == 0:
            return out
        pos = np.searchsorted(selected, indices)
        ok = (pos < width) & (selected[np.minimum(pos, width - 1)] == indices)
        out[row_ids[ok], pos[ok]] = values[ok]
        return out
    if num_features is None:
        raise ValueError("need selected slots or num_features")
    out = np.zeros((n, num_features), np.float32)
    out[row_ids, indices] = values
    return out
