"""Stable token hashing + sparse-row helpers for the hashing trick.

The reference's string featurization hashes tokens with Spark's HashingTF
into 2^18 slots and keeps only slots seen non-zero in the fit corpus
(AssembleFeatures.scala:198-224: BitSet reduce + VectorSlicer).  Slot
selection is what makes the TPU path dense-friendly: XLA is dense-first, so
instead of materializing 262144-wide batches we select the observed slots
once at fit time and emit a dense (rows, n_selected) block.

Sparse rows (pre-selection) are represented as (indices:int32, values:float32)
tuples in an object column — the host-side analogue of Spark's SparseVector.
"""

from __future__ import annotations

import zlib
from typing import Iterable, Optional, Sequence

import numpy as np


def stable_hash(token: str) -> int:
    """Process-stable 32-bit token hash (crc32; Python's hash() is salted)."""
    return zlib.crc32(token.encode("utf-8"))


def hash_tokens_to_slots(tokens: Iterable[str], num_features: int) -> np.ndarray:
    """Map tokens to slot ids in [0, num_features)."""
    return np.asarray([stable_hash(t) % num_features for t in tokens],
                      dtype=np.int64)


def sparse_count_row(tokens: Sequence[str], num_features: int,
                     binary: bool = False) -> tuple[np.ndarray, np.ndarray]:
    """One row of term counts as (sorted unique indices, counts)."""
    if len(tokens) == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.float32))
    slots = hash_tokens_to_slots(tokens, num_features)
    idx, counts = np.unique(slots, return_counts=True)
    vals = (np.ones(len(idx), np.float32) if binary
            else counts.astype(np.float32))
    return idx.astype(np.int32), vals


def nonzero_slots(sparse_rows: Iterable[tuple[np.ndarray, np.ndarray]]) -> np.ndarray:
    """Union of observed slot ids over the corpus (the BitSet reduce)."""
    seen: set[int] = set()
    for idx, _ in sparse_rows:
        seen.update(int(i) for i in idx)
    return np.asarray(sorted(seen), dtype=np.int32)


def densify_sparse_column(col: np.ndarray,
                          selected: Optional[np.ndarray] = None,
                          num_features: Optional[int] = None) -> np.ndarray:
    """Materialize sparse rows as a dense float32 matrix.

    With `selected`, emit one dense column per selected slot (the
    VectorSlicer path); otherwise emit the full `num_features` width.
    """
    n = len(col)
    if selected is not None:
        width = len(selected)
        out = np.zeros((n, width), np.float32)
        if width == 0:
            return out
        for r, (idx, vals) in enumerate(col):
            if len(idx) == 0:
                continue
            pos = np.searchsorted(selected, idx)
            ok = (pos < width) & (selected[np.minimum(pos, width - 1)] == idx)
            out[r, pos[ok]] = vals[ok]
        return out
    if num_features is None:
        raise ValueError("need selected slots or num_features")
    out = np.zeros((n, num_features), np.float32)
    for r, (idx, vals) in enumerate(col):
        out[r, idx] = vals
    return out
