"""Draft/target pairing for speculative decoding (models/generate.py).

The engine's contract is architectural, not statistical: ANY draft LM
with the same vocabulary yields byte-identical greedy output (and the
target's sampling distribution, via rejection sampling) — only the
acceptance rate, and hence the speedup, depends on how well the draft
predicts the target.  This module builds the zero-training draft that
works out of the box: a **layer-truncated self-draft** that runs the
target's own first `n_layers` blocks and re-uses its embedding /
final-norm / lm-head weights (the LayerSkip / early-exit construction).
Nothing is copied — the draft bundle aliases the target's arrays, so a
draft adds no parameter memory beyond its own KV cache.

Why truncation beats a separately-trained small LM here: the truncated
stack computes a prefix of the exact same residual stream the target
reads its logits from, so agreement is high wherever the late blocks
mostly refine rather than overturn the early prediction — the common
regime for confident tokens, which are exactly the tokens speculation
can batch.  And it needs no second checkpoint in the zoo.

`soften_late_blocks` is the bench/test counterpart: it scales the
residual-path *output* projections (attention proj, MLP down) of the
target's late blocks toward zero, making the target provably
draft-friendly — the truncated draft then agrees almost always, so
bench speedups and acceptance-rate assertions are stable across seeds
while greedy outputs remain byte-identical by construction (the
speedup claim is never assumed, always measured).
"""

from __future__ import annotations

import dataclasses

from mmlspark_tpu.models.bundle import ModelBundle


def _lm_fields(bundle: ModelBundle) -> tuple[int, str]:
    cfg = bundle.config
    if "n_layers" not in cfg or "vocab_size" not in cfg:
        raise ValueError(
            f"architecture {bundle.architecture!r} is not a generatable "
            "LM bundle (no n_layers/vocab_size config)")
    return int(cfg["n_layers"]), str(cfg.get("mlp_impl", "dense"))


def truncated_draft_bundle(bundle: ModelBundle,
                           n_layers: int = 1) -> ModelBundle:
    """A draft LM that is the target's first `n_layers` blocks.

    Shares (aliases) the target's tok_embed / pos_embed / early
    block{i}_w / final_norm_w / lm_head arrays; the returned bundle's
    config differs from the target's only in `n_layers`.  Pass the
    result to TextGenerator.set_draft_bundle (or its module/variables
    straight into DecodeEngine) alongside `specTokens`.

    MoE targets are rejected up front — step-by-step decode routes a
    different capacity group than batched verify, the same reason
    DecodeEngine refuses MoE drafts.
    """
    total, mlp_impl = _lm_fields(bundle)
    if mlp_impl == "moe":
        raise ValueError(
            "speculative decoding does not support MoE models: per-step "
            "routing and batched verify route different capacity groups")
    if not 1 <= n_layers <= total:
        raise ValueError(
            f"draft n_layers must be in [1, {total}], got {n_layers}")
    params = bundle.variables["params"]
    kept = {"tok_embed": params["tok_embed"],
            "pos_embed": params["pos_embed"],
            "final_norm_w": params["final_norm_w"],
            "lm_head": params["lm_head"]}
    for i in range(n_layers):
        kept[f"block{i}_w"] = params[f"block{i}_w"]
    variables = dict(bundle.variables)
    variables["params"] = kept
    config = dict(bundle.config)
    config["n_layers"] = n_layers
    # partition metadata intentionally dropped: draft params replicate
    # (DRAFT_KV_CACHE_SPEC rides the data axis only)
    metadata = {"speculative": {"draft_of": bundle.architecture,
                                "target_layers": total,
                                "draft_layers": n_layers}}
    return ModelBundle(bundle.architecture, config, variables, metadata)


def soften_late_blocks(bundle: ModelBundle, keep_layers: int,
                       factor: float = 0.05) -> ModelBundle:
    """A copy of `bundle` whose blocks `keep_layers..` have their
    residual-path output projections (attention proj, MLP down) scaled
    by `factor` — the late blocks then barely perturb the residual
    stream, so `truncated_draft_bundle(result, keep_layers)` agrees
    with it on almost every greedy token.  Bench/test harness only;
    a real checkpoint's acceptance rate is whatever it is."""
    import numpy as np

    total, _ = _lm_fields(bundle)
    if not 1 <= keep_layers <= total:
        raise ValueError(
            f"keep_layers must be in [1, {total}], got {keep_layers}")
    params = dict(bundle.variables["params"])
    for i in range(keep_layers, total):
        block = {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in params[f"block{i}_w"].items()}
        for name in ("proj", "mlp_down"):
            if name in block:
                block[name] = {k: np.asarray(v) * factor
                               for k, v in block[name].items()}
        params[f"block{i}_w"] = block
    variables = dict(bundle.variables)
    variables["params"] = params
    return dataclasses.replace(bundle, variables=variables,
                               metadata=dict(bundle.metadata or {}))
