"""Model repository client: manifest -> sha256-verified local cache.

TPU-native counterpart of the reference's downloader
(ModelDownloader.scala:24-242, Schema.scala:20-96): repositories list
`.meta` JSON schemas describing models (name, dataset, type, uri, sha256,
size, layer names); downloading copies the payload into a local repo,
verifies the hash (Schema.scala:35-41), writes the updated `.meta`, and
skips models already cached with a matching hash
(ModelDownloader.scala:169-181).

The payload format is a `.tpubundle` zip of a ModelBundle directory
(models/bundle.py) instead of an opaque CNTK graph file; `layer_names` and
`input_shape` ride in the bundle metadata so ImageFeaturizer can cut heads
without probing the graph over JNI.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import zipfile
from typing import Iterable, Optional

from mmlspark_tpu.models.bundle import ModelBundle, load_bundle, save_bundle


class ModelNotFoundError(FileNotFoundError):
    """Reference ModelNotFoundException (ModelDownloader.scala:36-40)."""


@dataclasses.dataclass
class ModelSchema:
    """Reference ModelSchema (Schema.scala:56-76)."""

    name: str
    dataset: str
    modelType: str
    uri: str
    hash: str
    size: int
    inputShape: Optional[list] = None
    numLayers: int = 0
    layerNames: list = dataclasses.field(default_factory=list)

    @property
    def filename(self) -> str:
        return _bundle_filename(self.name, self.dataset)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "ModelSchema":
        return ModelSchema(**d)


def _safe_component(part: str) -> str:
    """Reject path-traversal in remote-supplied schema fields: a hostile
    manifest must not be able to steer the cache target outside the cache
    dir (the manifest's sha256 is attacker-chosen, so it offers no
    protection)."""
    if (not part or part in (".", "..") or "/" in part or "\\" in part
            or os.path.basename(part) != part):
        raise ValueError(f"unsafe model schema path component: {part!r}")
    return part


def _bundle_filename(name: str, dataset: str) -> str:
    return f"{_safe_component(name)}_{_safe_component(dataset)}.tpubundle"


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


# --------------------------------------------------------------------------
# bundle <-> single-file payload
# --------------------------------------------------------------------------

def pack_bundle(bundle_dir: str, out_path: str) -> str:
    """Zip a bundle directory deterministically (sorted names, zeroed
    timestamps) so equal bundles hash equal."""
    with zipfile.ZipFile(out_path, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _, names in sorted(os.walk(bundle_dir)):
            for name in sorted(names):
                full = os.path.join(root, name)
                rel = os.path.relpath(full, bundle_dir)
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                with open(full, "rb") as f:
                    zf.writestr(info, f.read())
    return out_path


def unpack_bundle(payload_path: str, out_dir: str) -> str:
    with zipfile.ZipFile(payload_path) as zf:
        zf.extractall(out_dir)
    return out_dir


# --------------------------------------------------------------------------
# repositories
# --------------------------------------------------------------------------

class LocalRepo:
    """Directory of .tpubundle payloads + .meta JSON schemas
    (the HDFSRepo analogue, ModelDownloader.scala:43-106)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def list_schemas(self) -> Iterable[ModelSchema]:
        out = []
        for name in sorted(os.listdir(self.path)):
            if name.endswith(".meta"):
                with open(os.path.join(self.path, name)) as f:
                    schema = ModelSchema.from_json(json.load(f))
                # metas store payload URIs relative to the repo dir (the
                # portable CDN layout); resolve for local reads
                if "://" not in schema.uri and not os.path.isabs(schema.uri):
                    schema = dataclasses.replace(
                        schema, uri=os.path.join(self.path, schema.uri))
                out.append(schema)
        return out

    def get_payload(self, schema: ModelSchema) -> bytes:
        path = schema.uri
        if not os.path.exists(path):
            raise ModelNotFoundError(path)
        with open(path, "rb") as f:
            return f.read()

    def add_model(self, bundle: ModelBundle, name: str, dataset: str,
                  model_type: str = "image") -> ModelSchema:
        """Publish a bundle into this repo (addBytes analogue)."""
        payload = os.path.join(self.path, _bundle_filename(name, dataset))
        with tempfile.TemporaryDirectory() as tmp:
            bdir = os.path.join(tmp, "bundle")
            save_bundle(bundle, bdir)
            pack_bundle(bdir, payload)
        meta = bundle.metadata or {}
        schema = ModelSchema(
            name=name, dataset=dataset, modelType=model_type,
            uri=payload, hash=sha256_file(payload),
            size=os.path.getsize(payload),
            inputShape=meta.get("input_shape"),
            numLayers=len(meta.get("layer_names", [])),
            layerNames=list(meta.get("layer_names", [])))
        with open(payload + ".meta", "w") as f:
            # portable layout: the stored URI is relative to the repo dir,
            # so the directory can be served over HTTP (export_manifest) or
            # moved; the returned schema carries the resolved absolute path
            json.dump({**schema.to_json(),
                       "uri": os.path.basename(payload)}, f, indent=1)
        return schema

    def export_manifest(self) -> str:
        """Write a MANIFEST listing the repo's .meta names, making the
        directory directly servable over HTTP for RemoteRepo (the
        reference's CDN layout, ModelDownloader.scala:109-157)."""
        metas = [n for n in sorted(os.listdir(self.path))
                 if n.endswith(".meta")]
        path = os.path.join(self.path, "MANIFEST")
        with open(path, "w") as f:
            f.write("\n".join(metas) + "\n")
        return path


class RemoteRepo:
    """HTTP(S) repository: MANIFEST lists .meta names
    (the DefaultModelRepo analogue, ModelDownloader.scala:109-157)."""

    def __init__(self, base_url: str, connect_timeout: float = 15.0,
                 read_timeout: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.connect_timeout = connect_timeout
        self.read_timeout = read_timeout

    def _fetch(self, rel: str, timeout: Optional[float] = None) -> bytes:
        # resilience-layer fetch: retry/backoff + the host's circuit
        # breaker, same policy surface as io/remote.py
        from mmlspark_tpu.resilience.net import fetch_url
        url = f"{self.base_url}/{rel}"
        return fetch_url(url, timeout=timeout or self.connect_timeout)

    def list_schemas(self) -> Iterable[ModelSchema]:
        manifest = self._fetch("MANIFEST").decode().split()
        out = []
        for meta_name in manifest:
            d = json.loads(self._fetch(meta_name).decode())
            out.append(ModelSchema.from_json(d))
        return out

    def get_payload(self, schema: ModelSchema) -> bytes:
        uri = schema.uri
        if "://" in uri:
            # absolute URI: may live under a subdirectory or another host
            # than base_url, but a remote-supplied .meta must not steer us
            # to file:///etc/... or internal services (SSRF) — http(s)
            # only, same trust level as base_url itself
            import urllib.parse
            if urllib.parse.urlparse(uri).scheme not in ("http", "https"):
                raise ModelNotFoundError(
                    f"refusing non-http(s) payload uri: {uri!r}")
        try:
            if "://" in uri:
                from mmlspark_tpu.resilience.net import fetch_url
                return fetch_url(uri, timeout=self.read_timeout)
            # large payloads get the (longer) read window
            return self._fetch(uri, timeout=self.read_timeout)
        except Exception as e:
            raise ModelNotFoundError(uri) from e


def pretrained_repo() -> LocalRepo:
    """The package's committed pretrained-model repository.

    The reference serves trained models from a CDN
    (ModelDownloader.scala:109-157); an air-gapped TPU build ships them as
    package data instead.  Holds four trained bundles published by
    scripts/train_zoo_model.py: ConvNet/UCIDigits and
    ResNetDigits/UCIDigits (real UCI handwritten-digits images, ~99%
    held-out accuracy each), TabularWDBC/WDBC (real UCI breast-cancer
    table), and TextSentiment/Reviews (TextFeaturizer chain + MLP head);
    each bundle's metadata records its dataset, accuracy, and — where
    scoring needs it — the featurization/standardization recipe.
    """
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "pretrained")
    repo = LocalRepo(path)
    if not list(repo.list_schemas()):
        raise ModelNotFoundError(
            f"pretrained repo at {path} is empty; regenerate with "
            f"scripts/train_zoo_model.py")
    return repo


# --------------------------------------------------------------------------
# the downloader
# --------------------------------------------------------------------------

class ModelDownloader:
    """Sync models from a repo into a local cache, verified by sha256."""

    def __init__(self, local_path: Optional[str] = None):
        self.local = LocalRepo(local_path or os.path.join(
            os.path.expanduser("~"), ".cache", "mmlspark_tpu", "models"))

    def local_models(self) -> list[ModelSchema]:
        return list(self.local.list_schemas())

    def remote_models(self, repo) -> list[ModelSchema]:
        return list(repo.list_schemas())

    def download_model(self, repo, schema: ModelSchema,
                       always_download: bool = False) -> ModelSchema:
        """Fetch + verify one model; returns the localized schema.

        Skips the fetch when a cached copy with the same hash exists
        (ModelDownloader.scala:169-181).
        """
        target = os.path.join(self.local.path, schema.filename)
        if (not always_download and os.path.exists(target)
                and sha256_file(target) == schema.hash):
            return self._localized(schema, target)
        data = repo.get_payload(schema)
        digest = hashlib.sha256(data).hexdigest()
        if digest != schema.hash:
            raise ValueError(
                f"downloaded hash {digest} does not match schema hash "
                f"{schema.hash} for model {schema.name} (Schema.scala:35-41)")
        tmp = f"{target}.{os.getpid()}.tmp"  # per-process: concurrent syncs
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, target)
        local_schema = self._localized(schema, target)
        with open(target + ".meta", "w") as f:
            json.dump(local_schema.to_json(), f, indent=1)
        return local_schema

    def download_by_name(self, repo, name: str,
                         always_download: bool = False) -> ModelSchema:
        """ModelDownloader.downloadByName (scala:236-242)."""
        for schema in repo.list_schemas():
            if schema.name == name:
                return self.download_model(repo, schema, always_download)
        raise ModelNotFoundError(name)

    def load_bundle(self, schema: ModelSchema) -> ModelBundle:
        """Unpack a localized schema's payload into a ModelBundle."""
        with tempfile.TemporaryDirectory() as tmp:
            unpack_bundle(schema.uri, tmp)
            return load_bundle(tmp)

    @staticmethod
    def _localized(schema: ModelSchema, target: str) -> ModelSchema:
        return dataclasses.replace(schema, uri=target)


# --------------------------------------------------------------------------
# built-in zoo
# --------------------------------------------------------------------------

_BUILTIN_SPECS = [
    # (name, dataset, architecture, config, input_shape, layer_names)
    ("ConvNet", "CIFAR10", "ConvNetCIFAR10", {},
     [1, 32, 32, 3], ["z", "dense1", "pool3", "pool2", "pool1"]),
    ("ResNet18", "ImageNet", "ResNet",
     {"stage_sizes": [2, 2, 2, 2], "widths": [64, 128, 256, 512]},
     [1, 224, 224, 3], ["z", "pool", "stage4", "stage3", "stage2", "stage1"]),
    ("ResNet50", "ImageNet", "ResNet",
     {"stage_sizes": [3, 4, 6, 3], "widths": [64, 128, 256, 512],
      "block_kind": "bottleneck"},
     [1, 224, 224, 3], ["z", "pool", "stage4", "stage3", "stage2", "stage1"]),
    ("MLP", "Generic", "MLPClassifier", {"hidden_sizes": [100]},
     [1, 16], ["z", "h0"]),
]


def create_builtin_repo(path: str, seed: int = 0,
                        include: Optional[list] = None) -> LocalRepo:
    """Materialize the built-in architecture zoo as a local repo.

    Weights are seed-initialized (the reference's zoo ships pretrained CNTK
    graphs from a CDN, tools/config.sh; in an air-gapped build the zoo
    carries architectures + integrity plumbing, and fine-tuning fills in
    weights via train/).  `include` limits materialization to the named
    models (big nets like ResNet50 take seconds to init + pack).
    """
    from mmlspark_tpu.models.definitions import build_model
    repo = LocalRepo(path)
    existing = {(s.name, s.dataset) for s in repo.list_schemas()}
    for name, dataset, arch, config, input_shape, layer_names in _BUILTIN_SPECS:
        if (name, dataset) in existing:
            continue
        if include is not None and name not in include:
            continue
        module = build_model(arch, config)
        bundle = ModelBundle.init(module, tuple(input_shape), seed=seed,
                                  metadata={"input_shape": input_shape,
                                            "layer_names": layer_names,
                                            "pretrained": False})
        repo.add_model(bundle, name, dataset)
    return repo
