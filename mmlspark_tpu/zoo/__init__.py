"""Model zoo: repository client with integrity-checked downloads
(reference downloader/) plus draft/target pairing for speculative
decoding (speculative.py)."""

from mmlspark_tpu.zoo.speculative import (
    soften_late_blocks,
    truncated_draft_bundle,
)
from mmlspark_tpu.zoo.downloader import (
    LocalRepo,
    ModelDownloader,
    ModelNotFoundError,
    ModelSchema,
    RemoteRepo,
    create_builtin_repo,
    pretrained_repo,
    pack_bundle,
    unpack_bundle,
)

__all__ = [
    "ModelSchema", "ModelDownloader", "LocalRepo", "RemoteRepo",
    "ModelNotFoundError", "create_builtin_repo", "pretrained_repo", "pack_bundle",
    "unpack_bundle", "truncated_draft_bundle", "soften_late_blocks",
]
