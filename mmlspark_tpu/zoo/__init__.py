"""Model zoo: repository client with integrity-checked downloads
(reference downloader/)."""

from mmlspark_tpu.zoo.downloader import (
    LocalRepo,
    ModelDownloader,
    ModelNotFoundError,
    ModelSchema,
    RemoteRepo,
    create_builtin_repo,
    pretrained_repo,
    pack_bundle,
    unpack_bundle,
)

__all__ = [
    "ModelSchema", "ModelDownloader", "LocalRepo", "RemoteRepo",
    "ModelNotFoundError", "create_builtin_repo", "pretrained_repo", "pack_bundle",
    "unpack_bundle",
]
