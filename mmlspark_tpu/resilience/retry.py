"""Retry policy: exponential backoff with full jitter, bounded by budgets.

The reference leaned on Spark's task-retry machinery for every transient
fault (a failed partition simply re-ran); a TPU-native pipeline has no
scheduler above it, so the retry loop lives here as an explicit policy
object.  Semantics:

  * exponential backoff `base * 2**(attempt-1)` capped at `max_backoff_s`,
    with FULL jitter (delay drawn uniformly from [0, backoff]) — the AWS
    architecture-blog result: full jitter minimizes total work under
    contention, and correlated retries are exactly what a preempted TPU
    slice hammering a checkpoint store produces;
  * retryable-exception CLASSIFICATION: timeouts, connection resets, and
    5xx/408/429 HTTP responses retry; any other 4xx (auth, not-found,
    bad-request) fails FAST — burning a backoff budget on a 403 only
    delays the operator's fix;
  * server-supplied `Retry-After` (429/503) overrides the computed
    backoff for that attempt;
  * two deadline budgets: per-attempt (`attempt_deadline_s`, offered to
    the callable as its timeout) and total (`total_deadline_s`, after
    which the policy stops sleeping and re-raises).

All time flows through `resilience.clock`, so tests run the whole schedule
on a VirtualClock with zero wall-clock sleeps.  Every attempt/giveup bumps
a counter through `observe.metrics`.
"""

from __future__ import annotations

import dataclasses
import random
import urllib.error
from typing import Any, Callable, Optional

from mmlspark_tpu import config
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import trace_event
from mmlspark_tpu.resilience.clock import Clock, get_clock

RETRY_MAX_ATTEMPTS = config.register(
    "MMLSPARK_TPU_RETRY_MAX_ATTEMPTS", 5,
    "retry policy: attempts before giving up (1 = no retries)", ptype=int)
RETRY_BASE_S = config.register(
    "MMLSPARK_TPU_RETRY_BASE_S", 0.5,
    "retry policy: first backoff interval, doubled per attempt",
    ptype=float)
RETRY_MAX_BACKOFF_S = config.register(
    "MMLSPARK_TPU_RETRY_MAX_BACKOFF_S", 30.0,
    "retry policy: backoff ceiling per attempt", ptype=float)
RETRY_TOTAL_DEADLINE_S = config.register(
    "MMLSPARK_TPU_RETRY_TOTAL_DEADLINE_S", 120.0,
    "retry policy: total budget (sleep + attempts) before giving up",
    ptype=float)
RETRY_ATTEMPT_DEADLINE_S = config.register(
    "MMLSPARK_TPU_RETRY_ATTEMPT_DEADLINE_S", 0.0,
    "retry policy: per-attempt timeout offered to the callable "
    "(0 = the callable's own timeout applies)", ptype=float)


class RetryBudgetExceeded(Exception):
    """All attempts (or the total deadline) were consumed; the last
    underlying error is chained as __cause__."""

    def __init__(self, message: str, attempts: int, elapsed_s: float):
        super().__init__(message)
        self.attempts = attempts
        self.elapsed_s = elapsed_s


def retryable_status(code: int) -> bool:
    """HTTP classification: 5xx and the two transient 4xx (408 request
    timeout, 429 too-many-requests) retry; every other 4xx fails fast."""
    return code in (408, 429) or 500 <= code < 600


def default_classify(exc: BaseException) -> bool:
    """True when `exc` is worth retrying.

    Conservative allow-list: network-shaped transients only.  Unknown
    exception types (ValueError, KeyError, ...) are program bugs, not
    faults — retrying them is noise.
    """
    from mmlspark_tpu.resilience.breaker import CircuitOpenError
    if isinstance(exc, CircuitOpenError):
        return False  # the breaker already said stop calling
    if isinstance(exc, urllib.error.HTTPError):
        return retryable_status(exc.code)
    if isinstance(exc, (TimeoutError, ConnectionError,
                        urllib.error.URLError)):
        return True
    return False


def retry_after_hint(exc: BaseException) -> Optional[float]:
    """Server-requested wait from a 429/503 `Retry-After` header (seconds
    form only; the HTTP-date form is ignored rather than parsed wrong)."""
    if not isinstance(exc, urllib.error.HTTPError):
        return None
    if exc.code not in (429, 503):
        return None
    raw = (exc.headers.get("Retry-After") if exc.headers is not None
           else None)
    if raw is None:
        return None
    try:
        return max(0.0, float(raw.strip()))
    except ValueError:
        return None


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """An immutable retry schedule; `call()` runs a callable under it."""

    max_attempts: int = 5
    base_s: float = 0.5
    max_backoff_s: float = 30.0
    total_deadline_s: float = 120.0
    attempt_deadline_s: float = 0.0    # 0 = callable's own timeout
    classify: Callable[[BaseException], bool] = default_classify
    seed: Optional[int] = None         # None = nondeterministic jitter
    name: str = "retry"                # counter/log namespace

    @staticmethod
    def from_config(name: str = "retry", **overrides) -> "RetryPolicy":
        """A policy from the MMLSPARK_TPU_RETRY_* registry variables."""
        fields = dict(
            max_attempts=int(RETRY_MAX_ATTEMPTS.current()),
            base_s=float(RETRY_BASE_S.current()),
            max_backoff_s=float(RETRY_MAX_BACKOFF_S.current()),
            total_deadline_s=float(RETRY_TOTAL_DEADLINE_S.current()),
            attempt_deadline_s=float(RETRY_ATTEMPT_DEADLINE_S.current()),
            name=name)
        fields.update(overrides)
        return RetryPolicy(**fields)

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter delay after failed attempt number `attempt` (1-based)."""
        ceiling = min(self.max_backoff_s,
                      self.base_s * (2.0 ** (attempt - 1)))
        return rng.uniform(0.0, ceiling)

    def call(self, fn: Callable[..., Any], *, breaker=None,
             clock: Optional[Clock] = None,
             on_retry: Optional[Callable[[int, BaseException, float],
                                         None]] = None) -> Any:
        """Run `fn` under this policy.

        `fn` is called as `fn()` or, when `attempt_deadline_s` is set, as
        `fn(timeout=remaining_attempt_budget)`.  A `breaker` (CircuitBreaker)
        gates each attempt and is fed the outcome.  `on_retry(attempt, exc,
        delay)` observes each scheduled retry.
        """
        clock = clock or get_clock()
        rng = random.Random(self.seed)
        start = clock.monotonic()
        attempt = 0
        while True:
            attempt += 1
            if breaker is not None:
                breaker.allow()   # raises CircuitOpenError when open
            inc_counter(f"{self.name}.attempts")
            try:
                if self.attempt_deadline_s > 0:
                    remaining = self.total_deadline_s - (clock.monotonic()
                                                         - start)
                    result = fn(timeout=max(0.001, min(
                        self.attempt_deadline_s, remaining)))
                else:
                    result = fn()
            except BaseException as exc:  # noqa: blanket on purpose —
                # classification decides; non-retryables re-raise below
                if breaker is not None:
                    breaker.record_failure(exc)
                elapsed = clock.monotonic() - start
                if not self.classify(exc):
                    inc_counter(f"{self.name}.non_retryable")
                    trace_event(f"{self.name}.attempt", cat="resilience",
                                attempt=attempt,
                                error=type(exc).__name__,
                                outcome="non_retryable")
                    raise
                if attempt >= self.max_attempts:
                    inc_counter(f"{self.name}.giveup")
                    trace_event(f"{self.name}.attempt", cat="resilience",
                                attempt=attempt,
                                error=type(exc).__name__,
                                outcome="giveup",
                                elapsed_s=round(elapsed, 3))
                    raise RetryBudgetExceeded(
                        f"{self.name}: gave up after {attempt} attempts "
                        f"({elapsed:.1f}s): {exc!r}", attempt,
                        elapsed) from exc
                delay = self.backoff_s(attempt, rng)
                hinted = retry_after_hint(exc)
                if hinted is not None:
                    delay = hinted
                if elapsed + delay > self.total_deadline_s:
                    inc_counter(f"{self.name}.giveup")
                    trace_event(f"{self.name}.attempt", cat="resilience",
                                attempt=attempt,
                                error=type(exc).__name__,
                                outcome="deadline_exceeded",
                                elapsed_s=round(elapsed, 3))
                    raise RetryBudgetExceeded(
                        f"{self.name}: total deadline "
                        f"{self.total_deadline_s:.1f}s exceeded after "
                        f"{attempt} attempts: {exc!r}", attempt,
                        elapsed) from exc
                inc_counter(f"{self.name}.retries")
                trace_event(f"{self.name}.attempt", cat="resilience",
                            attempt=attempt, error=type(exc).__name__,
                            outcome="retry_scheduled",
                            delay_s=round(delay, 3))
                if on_retry is not None:
                    on_retry(attempt, exc, delay)
                get_logger("resilience").debug(
                    "%s: attempt %d failed (%r); retrying in %.2fs",
                    self.name, attempt, exc, delay)
                clock.sleep(delay)
            else:
                if breaker is not None:
                    breaker.record_success()
                if attempt > 1:
                    inc_counter(f"{self.name}.recovered")
                    trace_event(f"{self.name}.attempt", cat="resilience",
                                attempt=attempt, outcome="recovered")
                return result


def retry_call(fn: Callable[..., Any], *, policy: Optional[RetryPolicy] = None,
               **kwargs) -> Any:
    """Convenience: run `fn` under `policy` (default: from config)."""
    return (policy or RetryPolicy.from_config()).call(fn, **kwargs)
