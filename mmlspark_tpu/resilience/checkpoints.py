"""Checkpoint rotation: keep-last-K, LATEST pointer, checksum validation.

The trainer's old layout was a single `checkpoint.msgpack` overwritten in
place — atomic per write, but one torn file (partial upload, disk-full,
chaos) meant TOTAL loss of progress, and a preempted worker restarting
against it would crash instead of falling back.  This module owns the
directory layout the trainer now writes:

    ckpt_dir/
      ckpt_0000000100.msgpack          payload (atomic tmp+rename)
      ckpt_0000000100.msgpack.sha256   sidecar checksum
      ckpt_0000000200.msgpack
      ckpt_0000000200.msgpack.sha256
      LATEST                           name of the newest checkpoint

Restore walks candidates newest-first (the LATEST pointer is an
optimization, not trusted): a checkpoint only qualifies if its sidecar
checksum matches the payload bytes, so a torn or bit-rotted file is
SKIPPED with a warning and a counter — never crashed on.  A legacy
`checkpoint.msgpack` (no sidecar) is accepted last for forward
compatibility with pre-rotation directories.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from mmlspark_tpu import config
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import trace_event, trace_span

CKPT_KEEP = config.register(
    "MMLSPARK_TPU_CKPT_KEEP", 3,
    "checkpoint rotation: how many validated checkpoints to keep",
    ptype=int)

_PREFIX = "ckpt_"
_SUFFIX = ".msgpack"
_LEGACY = "checkpoint.msgpack"
LATEST = "LATEST"


def checkpoint_name(step: int) -> str:
    return f"{_PREFIX}{step:010d}{_SUFFIX}"


def step_of(name: str) -> int:
    return int(name[len(_PREFIX):-len(_SUFFIX)])


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn file HERE


def sweep_orphan_tmps(ckpt_dir: str) -> int:
    """Delete stray `*.tmp` files from a writer killed mid-write.

    The atomic tmp+rename protocol means a crash can only ever leave
    `.tmp` orphans — ignorable but previously immortal, so a directory
    that survived several preemptions slowly accreted junk.  Swept on
    every rotation open (write and restore walk); safe because the
    rotation layout has exactly one writer (the coordinator's writer
    thread) and sweeps never run concurrently with its renames.
    """
    if not os.path.isdir(ckpt_dir):
        return 0
    swept = 0
    for name in os.listdir(ckpt_dir):
        if not name.endswith(".tmp"):
            continue
        try:
            os.remove(os.path.join(ckpt_dir, name))
            swept += 1
        except FileNotFoundError:
            continue
    if swept:
        inc_counter("checkpoint.orphan_tmps_swept", swept)
        trace_event("checkpoint.orphan_tmps_swept", cat="resilience",
                    dir=ckpt_dir, count=swept)
        get_logger("resilience").warning(
            "swept %d orphaned .tmp file(s) from %s (writer killed "
            "mid-write)", swept, ckpt_dir)
    return swept


def write_checkpoint(ckpt_dir: str, step: int, data: bytes,
                     keep: Optional[int] = None,
                     meta: Optional[dict] = None) -> str:
    """Write one checkpoint + checksum sidecar, advance LATEST, prune.

    Returns the payload path.  The sidecar is written BEFORE the payload
    rename lands and LATEST moves only after both, so every state a crash
    can leave behind is either ignorable (orphan tmp/sidecar) or valid.
    `meta` (topology/batch info for elastic resume) lands in a
    `.meta.json` sidecar — advisory, not checksummed: restore treats a
    missing or unreadable meta as "no adjustment", never as corruption.
    """
    with trace_span("checkpoint.write", cat="checkpoint", step=step,
                    bytes=len(data)):
        os.makedirs(ckpt_dir, exist_ok=True)
        sweep_orphan_tmps(ckpt_dir)
        name = checkpoint_name(step)
        path = os.path.join(ckpt_dir, name)
        _atomic_write(path + ".sha256", _sha256(data).encode())
        if meta is not None:
            _atomic_write(path + ".meta.json",
                          json.dumps(meta, sort_keys=True).encode())
        _atomic_write(path, data)
        _atomic_write(os.path.join(ckpt_dir, LATEST), name.encode())
        # chaos may tear what we just wrote — payload, sidecar, or the
        # LATEST pointer (simulating partial upload / crash-adjacent
        # corruption); restore-side validation absorbs all three
        from mmlspark_tpu.resilience.chaos import get_injector
        get_injector().maybe_tear_checkpoint(path)
        inc_counter("checkpoint.writes")
        prune(ckpt_dir,
              keep if keep is not None else int(CKPT_KEEP.current()))
        # post-rotation chaos hook: scripted scenario tears (payload /
        # sidecar / LATEST pointer) land AFTER prune, so the torn state
        # stays on disk for the next restore to prove it skips it
        get_injector().after_checkpoint_write(path)
        return path


def checkpoint_meta(path: Optional[str]) -> Optional[dict]:
    """The `.meta.json` sidecar of a checkpoint payload path, or None.

    Advisory by design: any read/parse failure returns None (the restore
    then proceeds without elastic adjustment) — meta corruption must
    never make an otherwise-valid checkpoint unrestorable."""
    if not path:
        return None
    try:
        with open(path + ".meta.json") as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def list_checkpoints(ckpt_dir: str) -> list[tuple[int, str]]:
    """[(step, path)] of rotation-layout checkpoints, newest first."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if name.startswith(_PREFIX) and name.endswith(_SUFFIX):
            try:
                out.append((step_of(name), os.path.join(ckpt_dir, name)))
            except ValueError:
                continue
    return sorted(out, reverse=True)


def is_valid(path: str) -> bool:
    """True when the payload matches its sidecar checksum."""
    sidecar = path + ".sha256"
    if not (os.path.exists(path) and os.path.exists(sidecar)):
        return False
    with open(sidecar) as f:
        expected = f.read().strip()
    with open(path, "rb") as f:
        return _sha256(f.read()) == expected


def latest_valid_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest checkpoint that passes validation, or None.

    Order: the LATEST pointer's target first (the common case), then all
    rotation checkpoints newest-first, then the legacy single-file layout.
    Invalid candidates are skipped with a warning, not raised on.
    """
    with trace_span("checkpoint.validate", cat="checkpoint"):
        sweep_orphan_tmps(ckpt_dir)
        candidates: list[str] = []
        pointer = os.path.join(ckpt_dir, LATEST)
        if os.path.exists(pointer):
            with open(pointer) as f:
                candidates.append(os.path.join(ckpt_dir, f.read().strip()))
        candidates += [p for _, p in list_checkpoints(ckpt_dir)]
        seen = set()
        log = get_logger("resilience")
        for path in candidates:
            if path in seen:
                continue
            seen.add(path)
            if is_valid(path):
                return path
            if os.path.exists(path):
                inc_counter("checkpoint.skipped_corrupt")
                trace_event("checkpoint.skipped_corrupt", cat="resilience",
                            path=path)
                log.warning("skipping corrupt/torn checkpoint %s "
                            "(checksum mismatch)", path)
        legacy = os.path.join(ckpt_dir, _LEGACY)
        if os.path.exists(legacy):
            return legacy  # pre-rotation layout: no sidecar to validate
        return None


def prune(ckpt_dir: str, keep: int) -> None:
    """Delete rotation checkpoints (and sidecars) beyond the newest `keep`.

    Only VALID checkpoints count against the budget: corrupt files are
    deleted outright rather than crowding out good ones."""
    if keep <= 0:
        return
    kept = 0
    for _, path in list_checkpoints(ckpt_dir):
        if kept < keep and is_valid(path):
            kept += 1
            continue
        for victim in (path, path + ".sha256", path + ".meta.json"):
            try:
                os.remove(victim)
            except FileNotFoundError:
                pass
        inc_counter("checkpoint.pruned")
