"""Resilience subsystem: retry/backoff, circuit breaking, chaos injection,
checkpoint rotation, and preemption handling.

The reference design outsourced all of this to Spark — task retry,
lineage-based recompute, straggler re-execution (arXiv:1804.04031).  A
TPU-native pipeline has no scheduler above it, so the policies live here
as first-class, individually testable pieces:

  clock.py        injectable clock (VirtualClock makes every test sleepless)
  retry.py        exponential backoff + full jitter, classification, budgets
  breaker.py      per-endpoint circuit breaker (closed/open/half-open)
  chaos.py        deterministic seeded fault injector (MMLSPARK_TPU_CHAOS_*)
                  + the declarative multi-fault Scenario DSL and runner
  net.py          the single urlopen seam (lint-enforced) + fetch_url
  checkpoints.py  keep-last-K rotation, LATEST pointer, checksum validation,
                  orphan-tmp sweep, elastic-resume meta sidecar
  ckpt_writer.py  async checkpoint writer thread (the ONE home of
                  training-path checkpoint serialization; lint-enforced)
  preemption.py   SIGTERM -> finish step -> emergency checkpoint -> Preempted
                  + the hung-step watchdog (bounded-wait step execution)

See docs/resilience.md for the operator-facing knobs.
"""

from mmlspark_tpu.resilience.breaker import (CircuitBreaker,
                                             CircuitOpenError,
                                             breakers_snapshot,
                                             get_breaker, reset_breakers)
from mmlspark_tpu.resilience.chaos import (ChaosInjector, Fault,
                                           InjectedNetworkError,
                                           InjectedStallError, Scenario,
                                           get_injector, reset_chaos,
                                           run_scenario, set_injector)
from mmlspark_tpu.resilience.checkpoints import (checkpoint_meta,
                                                 latest_valid_checkpoint,
                                                 list_checkpoints,
                                                 sweep_orphan_tmps,
                                                 write_checkpoint)
from mmlspark_tpu.resilience.ckpt_writer import (CheckpointWriteError,
                                                 CheckpointWriter)
from mmlspark_tpu.resilience.clock import (Clock, VirtualClock, get_clock,
                                           set_clock)
from mmlspark_tpu.resilience.net import fetch_url, http_get
from mmlspark_tpu.resilience.preemption import (HungStepError, Preempted,
                                                PreemptionGuard, StepWatchdog)
from mmlspark_tpu.resilience.retry import (RetryBudgetExceeded, RetryPolicy,
                                           default_classify, retry_call,
                                           retryable_status)

__all__ = [
    "CircuitBreaker", "CircuitOpenError", "breakers_snapshot",
    "get_breaker", "reset_breakers",
    "ChaosInjector", "Fault", "InjectedNetworkError", "InjectedStallError",
    "Scenario", "get_injector", "reset_chaos", "run_scenario",
    "set_injector",
    "checkpoint_meta", "latest_valid_checkpoint", "list_checkpoints",
    "sweep_orphan_tmps", "write_checkpoint",
    "CheckpointWriteError", "CheckpointWriter",
    "Clock", "VirtualClock", "get_clock", "set_clock",
    "fetch_url", "http_get",
    "HungStepError", "Preempted", "PreemptionGuard", "StepWatchdog",
    "RetryBudgetExceeded", "RetryPolicy", "default_classify", "retry_call",
    "retryable_status",
]
