"""Per-endpoint circuit breaker: closed -> open -> half-open probe.

Retry policies protect a single call; the breaker protects the FLEET.
When an endpoint (an object-store host, a model-repo CDN) has failed N
consecutive times, every further call is refused instantly
(`CircuitOpenError`) instead of each caller independently burning a full
backoff budget against a dead host — the difference between an ingestion
job that fails in milliseconds with a clear diagnosis and one that takes
minutes to die.  After `reset_s` of cooldown one PROBE call is let
through (half-open): success closes the circuit, failure re-opens it and
restarts the cooldown.

State transitions and refusals are counted through `observe.metrics`
(`breaker.<event>`); cooldowns read `resilience.clock`, so breaker tests
run on a VirtualClock.  Instances are thread-safe; `get_breaker(endpoint)`
returns the process-wide breaker for an endpoint key (one per host).
"""

from __future__ import annotations

import threading
from typing import Optional

from mmlspark_tpu import config
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import trace_event
from mmlspark_tpu.resilience.clock import Clock, get_clock

BREAKER_THRESHOLD = config.register(
    "MMLSPARK_TPU_BREAKER_THRESHOLD", 5,
    "circuit breaker: consecutive failures that open the circuit "
    "(0 disables breaking entirely)", ptype=int)
BREAKER_RESET_S = config.register(
    "MMLSPARK_TPU_BREAKER_RESET_S", 30.0,
    "circuit breaker: cooldown before the half-open probe", ptype=float)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

# numeric form of the state for gauges (Prometheus samples are floats):
# 0 = closed (healthy), 1 = half-open (probing), 2 = open (shedding)
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitOpenError(ConnectionError):
    """Refused without calling: the endpoint's circuit is open."""

    def __init__(self, endpoint: str, retry_in_s: float):
        super().__init__(
            f"circuit open for endpoint {endpoint!r}; "
            f"probe allowed in {max(0.0, retry_in_s):.1f}s")
        self.endpoint = endpoint
        self.retry_in_s = retry_in_s


class CircuitBreaker:
    """One endpoint's failure gate.  Use `allow()` before the call and
    `record_success()` / `record_failure()` after — or let
    `RetryPolicy.call(..., breaker=...)` drive all three."""

    def __init__(self, endpoint: str, threshold: Optional[int] = None,
                 reset_s: Optional[float] = None,
                 clock: Optional[Clock] = None):
        self.endpoint = endpoint
        self._threshold = threshold
        self._reset_s = reset_s
        self._clock = clock
        self._lock = threading.Lock()
        self.state = CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0

    # config is re-read per call so tests (and live operators) can tune
    # knobs without rebuilding the breaker registry
    @property
    def threshold(self) -> int:
        return self._threshold if self._threshold is not None \
            else int(BREAKER_THRESHOLD.current())

    @property
    def reset_s(self) -> float:
        return self._reset_s if self._reset_s is not None \
            else float(BREAKER_RESET_S.current())

    def _now(self) -> float:
        return (self._clock or get_clock()).monotonic()

    def retry_in_s(self) -> float:
        """Seconds until the next half-open probe would be allowed (0 when
        closed or already due) — the time-to-retry the state gauges and
        `Retry-After` surfaces read."""
        with self._lock:
            if self.state != OPEN:
                return 0.0
            return max(0.0, self.reset_s - (self._now() - self._opened_at))

    def snapshot(self) -> dict:
        """Point-in-time state for the observability exports: state name +
        numeric code, consecutive failures, and time-to-retry."""
        retry = self.retry_in_s()
        with self._lock:
            return {"state": self.state,
                    "state_code": STATE_CODES[self.state],
                    "consecutive_failures": self.consecutive_failures,
                    "retry_in_s": round(retry, 3)}

    def _gauge_state(self) -> None:
        """Record the breaker's state as run gauges (call on transitions,
        holding no lock): `breaker.<endpoint>.state` makes a trip visible
        in run_summary.json and Prometheus, not just as an event."""
        from mmlspark_tpu.observe.telemetry import active_run
        run = active_run()
        if run is not None:
            run.gauge(f"breaker.{self.endpoint}.state",
                      STATE_CODES[self.state])
            run.gauge(f"breaker.{self.endpoint}.retry_in_s",
                      self.retry_in_s())

    def allow(self) -> None:
        """Gate one attempt: no-op when closed, raises when open, lets a
        single probe through once the cooldown has elapsed."""
        if self.threshold <= 0:
            return
        with self._lock:
            if self.state == CLOSED:
                return
            waited = self._now() - self._opened_at
            if self.state == OPEN and waited >= self.reset_s:
                self.state = HALF_OPEN
                inc_counter("breaker.half_open")
                trace_event("breaker.half_open", cat="resilience",
                            endpoint=self.endpoint,
                            waited_s=round(waited, 3))
                get_logger("resilience").info(
                    "breaker %s: half-open probe after %.1fs",
                    self.endpoint, waited)
            elif self.state == HALF_OPEN:
                # a probe is already in flight; refuse concurrent callers
                # (they would defeat the single-probe semantics)
                inc_counter("breaker.refused")
                trace_event("breaker.refused", cat="resilience",
                            endpoint=self.endpoint, state=HALF_OPEN)
                raise CircuitOpenError(self.endpoint, self.reset_s)
            else:
                inc_counter("breaker.refused")
                trace_event("breaker.refused", cat="resilience",
                            endpoint=self.endpoint, state=OPEN)
                raise CircuitOpenError(self.endpoint,
                                       self.reset_s - waited)
        # gauges outside the lock (they re-read state via retry_in_s)
        self._gauge_state()
        return  # this caller IS the probe

    def record_success(self) -> None:
        with self._lock:
            changed = self.state != CLOSED
            if changed:
                inc_counter("breaker.closed")
                trace_event("breaker.closed", cat="resilience",
                            endpoint=self.endpoint, outcome="probe_ok")
                get_logger("resilience").info(
                    "breaker %s: closed after successful probe",
                    self.endpoint)
            self.state = CLOSED
            self.consecutive_failures = 0
        if changed:
            self._gauge_state()

    def record_failure(self, exc: Optional[BaseException] = None) -> None:
        if self.threshold <= 0:
            return
        if isinstance(exc, CircuitOpenError):
            return  # a refusal is not new evidence against the endpoint
        with self._lock:
            self.consecutive_failures += 1
            trip = (self.state == HALF_OPEN
                    or self.consecutive_failures >= self.threshold)
            opened = trip and self.state != OPEN
            if opened:
                self.state = OPEN
                self._opened_at = self._now()
                inc_counter("breaker.opened")
                trace_event("breaker.opened", cat="resilience",
                            endpoint=self.endpoint,
                            failures=self.consecutive_failures,
                            error=type(exc).__name__ if exc else None)
                get_logger("resilience").warning(
                    "breaker %s: OPEN after %d consecutive failures "
                    "(last: %r); cooling down %.1fs", self.endpoint,
                    self.consecutive_failures, exc, self.reset_s)
            elif trip:
                self._opened_at = self._now()  # failed probe: restart cooldown
        if opened:
            self._gauge_state()


_breakers: dict[str, CircuitBreaker] = {}
_registry_lock = threading.Lock()


def get_breaker(endpoint: str) -> CircuitBreaker:
    """The process-wide breaker for an endpoint key (e.g. a URL's host)."""
    with _registry_lock:
        breaker = _breakers.get(endpoint)
        if breaker is None:
            breaker = _breakers[endpoint] = CircuitBreaker(endpoint)
        return breaker


def register_breaker(breaker: CircuitBreaker) -> CircuitBreaker:
    """Put an externally constructed breaker (custom threshold/clock —
    e.g. the serve router's per-replica ejection breakers) into the
    process registry so `breakers_snapshot()` and the Prometheus
    exposition see it like any other endpoint.  Last registration for an
    endpoint key wins."""
    with _registry_lock:
        _breakers[breaker.endpoint] = breaker
    return breaker


def breakers_snapshot() -> dict[str, dict]:
    """Every registered breaker's `snapshot()` by endpoint — the pull
    surface observe/export.py renders as per-endpoint Prometheus gauges
    (`mmlspark_tpu_breaker_state{endpoint=...}` etc.)."""
    with _registry_lock:
        breakers = list(_breakers.items())
    return {endpoint: b.snapshot() for endpoint, b in breakers}


def reset_breakers() -> None:
    """Drop all breaker state (test isolation)."""
    with _registry_lock:
        _breakers.clear()
