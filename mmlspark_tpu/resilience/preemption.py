"""Preemption guard: turn SIGTERM into a clean checkpoint-and-exit.

TPU VMs (and spot/preemptible capacity generally) get a SIGTERM with a
short grace window before the plug is pulled.  The guard installs a
handler that only sets a flag — the in-flight jitted step is never
interrupted mid-collective — and the training loop checks the flag at
the next step boundary, writes an emergency checkpoint, and raises
`Preempted`.  A job runner catches `Preempted` and exits 0; on the next
start, `fit_arrays(..., resume=True)` picks up from the newest valid
checkpoint.

The handler chains to any previously installed SIGTERM handler, and the
guard restores it on exit (context manager), so the framework never
swallows the application's own shutdown hooks.  Installation is skipped
off the main thread (signal.signal would raise) — there the flag can
still be set by `request()` (e.g. a cluster-notice poller).

`StepWatchdog` is the second half of the hang story: SIGTERM covers the
*planned* death, the watchdog covers the silent one — a wedged device, a
deadlocked collective a peer never joined, a driver stall.  It runs one
training step with a bounded wall-clock wait (the same worker-thread
pattern as `parallel/distributed.run_collective`); past the deadline it
raises `HungStepError` so the trainer can write an emergency checkpoint
of the last completed state and abort cleanly — and the recovery
supervisor (train/supervisor.py) can restart-and-resume — instead of
the job wedging forever inside an opaque device wait.
"""

from __future__ import annotations

import signal
import threading
from typing import Any, Callable, Optional

from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import trace_event


class Preempted(Exception):
    """Training stopped cleanly at a step boundary after SIGTERM; an
    emergency checkpoint for `step` was written to `ckpt_dir`."""

    def __init__(self, step: int, ckpt_dir: Optional[str]):
        super().__init__(
            f"preempted at step {step}; emergency checkpoint in "
            f"{ckpt_dir!r} — restart with resume=True to continue")
        self.step = step
        self.ckpt_dir = ckpt_dir


class HungStepError(RuntimeError):
    """A training step did not complete within the watchdog deadline —
    the device/step is stalled (wedged collective, dead peer, driver
    hang).  The trainer writes a best-effort emergency checkpoint before
    letting this escape; a supervisor restarts and resumes."""

    def __init__(self, step: int, deadline_s: float,
                 ckpt_dir: Optional[str] = None):
        self.step = step
        self.deadline_s = deadline_s
        self.ckpt_dir = ckpt_dir
        msg = (f"training step {step} stalled past the {deadline_s:.1f}s "
               f"watchdog deadline — device/collective likely wedged")
        if ckpt_dir:
            msg += (f"; restart with resume=True against {ckpt_dir} "
                    f"(or run under RecoverySupervisor) to continue")
        super().__init__(msg)


class StepWatchdog:
    """Bounded-wait execution of one training step.

    `run(fn, step)` executes `fn` on a worker thread and waits at most
    `deadline_s` wall seconds; on expiry it raises `HungStepError` and
    abandons the (daemonic) worker — for a real device hang the process
    is expected to abort and resume from checkpoint, exactly like
    `run_collective`'s timeout contract.  The callable must therefore
    *synchronize* on the step's results (block_until_ready) so an
    async-dispatched-but-never-finishing step counts as hung."""

    def __init__(self, deadline_s: float):
        if deadline_s <= 0:
            raise ValueError(
                f"watchdog deadline must be positive, got {deadline_s}")
        self.deadline_s = float(deadline_s)

    def run(self, fn: Callable[[], Any], step: int,
            ckpt_dir: Optional[str] = None) -> Any:
        result: dict = {}
        error: list = []

        def work():
            try:
                result["value"] = fn()
            except BaseException as e:  # surfaced to the caller below
                error.append(e)

        worker = threading.Thread(target=work, daemon=True,
                                  name=f"step-watchdog-{step}")
        worker.start()
        worker.join(self.deadline_s)
        if worker.is_alive():
            inc_counter("watchdog.hung_steps")
            trace_event("watchdog.hung_step", cat="resilience", step=step,
                        deadline_s=self.deadline_s)
            get_logger("resilience").error(
                "watchdog: step %d stalled past %.1fs deadline", step,
                self.deadline_s)
            raise HungStepError(step, self.deadline_s, ckpt_dir)
        if error:
            raise error[0]
        return result["value"]


class PreemptionGuard:
    """Context manager: SIGTERM -> `triggered` flag, restored on exit.

    `install=False` keeps the signal table untouched (no checkpoint dir =
    nowhere to save; the default SIGTERM disposition should stand) while
    still providing the flag object for uniform loop code."""

    def __init__(self, install: bool = True):
        self.triggered = False
        self._previous = None
        self._installed = False
        self._install = install

    def request(self) -> None:
        """Flag a preemption without a signal (pollers, tests)."""
        self.triggered = True

    def _handler(self, signum, frame) -> None:
        self.triggered = True
        inc_counter("preempt.sigterm")
        trace_event("preempt.sigterm", cat="resilience")
        get_logger("resilience").warning(
            "SIGTERM received: finishing the in-flight step, then writing "
            "an emergency checkpoint")
        if callable(self._previous):
            self._previous(signum, frame)

    def __enter__(self) -> "PreemptionGuard":
        if (self._install
                and threading.current_thread() is threading.main_thread()):
            self._previous = signal.signal(signal.SIGTERM, self._handler)
            self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM,
                          self._previous if self._previous is not None
                          else signal.SIG_DFL)
            self._installed = False
        return None
