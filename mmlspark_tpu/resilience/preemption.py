"""Preemption guard: turn SIGTERM into a clean checkpoint-and-exit.

TPU VMs (and spot/preemptible capacity generally) get a SIGTERM with a
short grace window before the plug is pulled.  The guard installs a
handler that only sets a flag — the in-flight jitted step is never
interrupted mid-collective — and the training loop checks the flag at
the next step boundary, writes an emergency checkpoint, and raises
`Preempted`.  A job runner catches `Preempted` and exits 0; on the next
start, `fit_arrays(..., resume=True)` picks up from the newest valid
checkpoint.

The handler chains to any previously installed SIGTERM handler, and the
guard restores it on exit (context manager), so the framework never
swallows the application's own shutdown hooks.  Installation is skipped
off the main thread (signal.signal would raise) — there the flag can
still be set by `request()` (e.g. a cluster-notice poller).
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import trace_event


class Preempted(Exception):
    """Training stopped cleanly at a step boundary after SIGTERM; an
    emergency checkpoint for `step` was written to `ckpt_dir`."""

    def __init__(self, step: int, ckpt_dir: Optional[str]):
        super().__init__(
            f"preempted at step {step}; emergency checkpoint in "
            f"{ckpt_dir!r} — restart with resume=True to continue")
        self.step = step
        self.ckpt_dir = ckpt_dir


class PreemptionGuard:
    """Context manager: SIGTERM -> `triggered` flag, restored on exit.

    `install=False` keeps the signal table untouched (no checkpoint dir =
    nowhere to save; the default SIGTERM disposition should stand) while
    still providing the flag object for uniform loop code."""

    def __init__(self, install: bool = True):
        self.triggered = False
        self._previous = None
        self._installed = False
        self._install = install

    def request(self) -> None:
        """Flag a preemption without a signal (pollers, tests)."""
        self.triggered = True

    def _handler(self, signum, frame) -> None:
        self.triggered = True
        inc_counter("preempt.sigterm")
        trace_event("preempt.sigterm", cat="resilience")
        get_logger("resilience").warning(
            "SIGTERM received: finishing the in-flight step, then writing "
            "an emergency checkpoint")
        if callable(self._previous):
            self._previous(signum, frame)

    def __enter__(self) -> "PreemptionGuard":
        if (self._install
                and threading.current_thread() is threading.main_thread()):
            self._previous = signal.signal(signal.SIGTERM, self._handler)
            self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM,
                          self._previous if self._previous is not None
                          else signal.SIG_DFL)
            self._installed = False
        return None
