"""The one raw-HTTP seam: every urlopen in the framework lives here.

`scripts/lint.py` forbids `urllib.request.urlopen` outside
`mmlspark_tpu/resilience/`, so all network reads funnel through
`http_get` — which is exactly where the chaos injector gets its hook
(`on_request`) and where chunked reads + per-request timeouts are
enforced once instead of per caller.  Callers compose policy on top:
`fetch_url` is the batteries-included form (retry policy + per-host
circuit breaker) that `io/remote.py` and `zoo/downloader.py` use.
"""

from __future__ import annotations

import io
import urllib.parse
import urllib.request
from typing import Optional

from mmlspark_tpu.resilience.breaker import get_breaker
from mmlspark_tpu.resilience.chaos import get_injector
from mmlspark_tpu.resilience.retry import RetryPolicy

_CHUNK = 1 << 20  # 1 MiB read granularity


def http_get(url: str, headers: Optional[dict] = None,
             timeout: Optional[float] = None) -> bytes:
    """One chunked GET with a per-request timeout; no retries — policy
    belongs to the caller (`fetch_url`).  Chaos faults inject here, below
    the policy layer, so retries/breakers see them exactly like real ones."""
    get_injector().on_request(url)
    req = urllib.request.Request(url, headers=headers or {})
    buf = io.BytesIO()
    with urllib.request.urlopen(req, timeout=timeout) as r:
        while True:
            chunk = r.read(_CHUNK)
            if not chunk:
                break
            buf.write(chunk)
    return buf.getvalue()


def fetch_url(url: str, headers: Optional[dict] = None,
              timeout: Optional[float] = None,
              policy: Optional[RetryPolicy] = None,
              breaker_key: Optional[str] = None) -> bytes:
    """`http_get` under a retry policy and the host's circuit breaker.

    `breaker_key` defaults to the URL's netloc, so every caller hitting
    the same host shares one breaker regardless of which layer it sits in.
    """
    policy = policy or RetryPolicy.from_config(name="remote.fetch")
    breaker = get_breaker(breaker_key
                          or urllib.parse.urlparse(url).netloc or url)

    def attempt(timeout: Optional[float] = timeout) -> bytes:
        # the policy passes timeout= only when attempt_deadline_s is set;
        # otherwise the caller's per-request timeout stands
        return http_get(url, headers=headers, timeout=timeout)

    return policy.call(attempt, breaker=breaker)
