"""Injectable clock: the one seam between resilience policies and time.

Every sleep and every deadline read in the resilience layer goes through
the process clock installed here, so tests drive retry backoff, breaker
cooldowns, and chaos stalls with a `VirtualClock` — deterministic and
instantaneous — while production uses the monotonic wall clock.  This is
the same move tf.data's input-pipeline tests make (arXiv:2101.12127):
fault-handling logic is only testable when time is a parameter.
"""

from __future__ import annotations

import time


class Clock:
    """The production clock: monotonic time + real sleeps."""

    def monotonic(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class VirtualClock(Clock):
    """A clock that only moves when slept on — test time, not wall time.

    `sleeps` records every requested sleep so tests can assert on the
    exact backoff schedule a policy produced.
    """

    def __init__(self, start: float = 0.0):
        self.now = float(start)
        self.sleeps: list[float] = []

    def monotonic(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        seconds = max(0.0, float(seconds))
        self.sleeps.append(seconds)
        self.now += seconds

    def advance(self, seconds: float) -> None:
        """Move time forward without recording a sleep (external waiting)."""
        self.now += float(seconds)


_clock: Clock = Clock()


def get_clock() -> Clock:
    """The process-wide clock every resilience policy reads."""
    return _clock


def set_clock(clock: Clock) -> Clock:
    """Install a clock (tests: a VirtualClock); returns the previous one."""
    global _clock
    previous = _clock
    _clock = clock
    return previous
