"""Async checkpoint writer: move save cost off the training step loop.

A synchronous rotation save costs the step loop the full
device→host fetch + flax serialization + sha256 + disk write every
`checkpoint_every_steps` — on big states that is tens of milliseconds of
pure host work the MXU spends idle (the TensorFlow system paper credits
background-thread user-level checkpointing for making multi-week runs
viable; this is that design).  The split here:

  * **step loop (caller)** — runs the gather collective (device-side,
    async dispatch, must stay on the main thread in lockstep under
    multi-host) and hands the resulting *device* tree to `submit()`.
    No D2H copy, no serialization, no disk I/O on the loop.
  * **writer thread** — `jax.device_get` (blocks HERE on the step's
    completion + D2H), `flax.serialization.to_bytes`, then
    `checkpoints.write_checkpoint` (atomic tmp/rename + sidecar + LATEST
    + prune).  The gather's output arrays are fresh jit outputs, so the
    step loop donating its state buffers never invalidates a pending
    write.

At most ONE write is in flight: a `submit()` racing a slow disk blocks
(backpressure — bounded memory, and rotation order stays submission
order).  `drain()` is the shutdown/preemption barrier: emergency and
final saves call it so the checkpoint is durable before the process
exits.  A writer-thread failure is latched and re-raised (as
`CheckpointWriteError`) from the next `submit()`/`drain()` — async never
silently drops a checkpoint.

This module is the ONE place training-path checkpoint serialization is
allowed to live: `scripts/lint.py` forbids `to_bytes`/`from_bytes`/
`write_checkpoint` calls inside `mmlspark_tpu/train/`, so a synchronous
save can never quietly creep back into the step loop.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from flax import serialization

from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import (active_tracer, trace_event,
                                        trace_span, tracing)
from mmlspark_tpu.resilience.checkpoints import (checkpoint_name,
                                                 write_checkpoint)


class CheckpointWriteError(RuntimeError):
    """A background checkpoint write failed; the original error is
    chained as __cause__.  Raised from the submit/drain AFTER the
    failure, so the step loop finds out at the next checkpoint boundary
    instead of never."""


def serialize_tree(host_tree: Any) -> bytes:
    """Host pytree -> msgpack bytes (the rotation payload format)."""
    return serialization.to_bytes(host_tree)


def read_checkpoint(template: Any, path: str) -> Any:
    """Load a rotation payload into `template`'s structure/shapes/dtypes
    (the restore-side counterpart; host arrays only, no device state)."""
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


class CheckpointWriter:
    """One background writer for one checkpoint directory.

    `submit(step, dev_tree, meta)` hands a (gathered, device-resident)
    state tree to the writer thread; `drain()` blocks until every
    submitted write is durable; `close()` drains and stops the thread.
    `sync=True` on submit is the one-call synchronous form
    (submit + drain) used for emergency/final saves.
    """

    def __init__(self, ckpt_dir: str, keep: Optional[int] = None,
                 name: str = "ckpt-writer"):
        self.ckpt_dir = ckpt_dir
        self.keep = keep
        self._name = name
        self._cond = threading.Condition()
        self._item: Optional[tuple] = None   # (step, dev_tree, meta)
        self._inflight = 0                   # submitted, not yet durable
        self._error: Optional[BaseException] = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- caller side -----------------------------------------------------
    def submit(self, step: int, dev_tree: Any, meta: Optional[dict] = None,
               sync: bool = False) -> str:
        """Queue one write; blocks only while a PREVIOUS write is still in
        flight (single-slot backpressure).  Returns the payload path the
        write will land at."""
        self._raise_pending()
        # the run's tracer is captured HERE, on the caller thread — the
        # writer thread never inherits contextvars (the same
        # capture-by-closure rule as the prefetch workers), and without
        # it the checkpoint.write spans would vanish from the run record
        tracer = active_tracer()
        with self._cond:
            while self._item is not None and self._error is None:
                self._cond.wait()
            self._raise_pending_locked()
            self._item = (int(step), dev_tree, meta, tracer)
            self._inflight += 1
            self._ensure_thread()
            self._cond.notify_all()
        if sync:
            self.drain()
        return os.path.join(self.ckpt_dir, checkpoint_name(int(step)))

    def drain(self) -> None:
        """Block until every submitted write is on disk (the shutdown /
        preemption barrier); surfaces any latched writer failure."""
        with self._cond:
            while self._inflight > 0 and self._error is None:
                self._cond.wait()
        self._raise_pending()

    def close(self, best_effort: bool = False) -> None:
        """Drain and stop the writer thread.  `best_effort=True` logs a
        latched failure instead of raising (finally-block form: never
        mask the exception already unwinding)."""
        try:
            self.drain()
        except CheckpointWriteError as e:
            if not best_effort:
                raise
            get_logger("resilience").warning(
                "checkpoint writer for %s closed with a failed write: %s",
                self.ckpt_dir, e.__cause__)
        finally:
            with self._cond:
                self._stop = True
                self._cond.notify_all()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None

    # -- error surfacing -------------------------------------------------
    def _raise_pending(self) -> None:
        with self._cond:
            self._raise_pending_locked()

    def _raise_pending_locked(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointWriteError(
                f"background checkpoint write to {self.ckpt_dir} "
                f"failed") from err

    # -- writer thread ---------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"mmlspark-{self._name}")
            self._thread.start()

    def _run(self) -> None:
        import contextlib

        import jax
        while True:
            with self._cond:
                while self._item is None and not self._stop:
                    self._cond.wait()
                if self._item is None and self._stop:
                    return
                step, dev_tree, meta, tracer = self._item
            try:
                # install the submitting run's tracer for this write so
                # checkpoint.write spans + chaos tear events land in it
                scope = tracing(tracer) if tracer is not None \
                    else contextlib.nullcontext()
                with scope, trace_span("checkpoint.async_write",
                                       cat="checkpoint", step=step):
                    # blocks HERE (writer thread) on step completion + D2H
                    host = jax.device_get(dev_tree)
                    write_checkpoint(self.ckpt_dir, step,
                                     serialize_tree(host),
                                     keep=self.keep, meta=meta)
                inc_counter("checkpoint.async_writes")
            except BaseException as e:  # latched; surfaced at next submit/drain
                with self._cond:
                    self._error = e
                inc_counter("checkpoint.async_write_failures")
                trace_event("checkpoint.async_write_failed",
                            cat="resilience", step=step, error=repr(e))
                get_logger("resilience").error(
                    "async checkpoint write (step %d, %s) failed: %s",
                    step, self.ckpt_dir, e)
            finally:
                with self._cond:
                    self._item = None
                    self._inflight -= 1
                    self._cond.notify_all()
