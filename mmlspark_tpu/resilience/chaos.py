"""Deterministic fault injection: the test harness for the resilience layer.

A resilience subsystem that is only ever exercised by real outages is
untested code.  `ChaosInjector` is a seeded, config-driven fault source
that the I/O and training layers consult at their hazard points:

  * `on_request(url)`   — before a network fetch: may raise a connection
    error or a (virtual-clock) stalled-read timeout;
  * `on_step(step)`     — per training step: may deliver one simulated
    SIGTERM preemption at a configured step;
  * `tear_file(path)`   — truncates a file in place, simulating a torn
    checkpoint from a crash or partial upload;
  * `maybe_tear_checkpoint(path)` — probabilistic form of the same, hooked
    into checkpoint rotation.

Determinism: one `random.Random(seed)` drives every probabilistic
decision, so a given seed + call sequence produces the SAME fault
pattern on every run — chaos tests are exactly reproducible, never
flaky-by-design.  Everything is off (zero rates, no seed needed) unless
the MMLSPARK_TPU_CHAOS_* variables turn it on.
"""

from __future__ import annotations

import os
import random
import signal
from typing import Optional

from mmlspark_tpu import config
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import trace_event
from mmlspark_tpu.resilience.clock import get_clock

CHAOS_SEED = config.register(
    "MMLSPARK_TPU_CHAOS_SEED", 0,
    "chaos injector: RNG seed (fault patterns are a pure function of "
    "seed + call order)", ptype=int)
CHAOS_NET_ERROR_RATE = config.register(
    "MMLSPARK_TPU_CHAOS_NET_ERROR_RATE", 0.0,
    "chaos injector: probability a network request raises a connection "
    "error (0 = off)", ptype=float)
CHAOS_STALL_RATE = config.register(
    "MMLSPARK_TPU_CHAOS_STALL_RATE", 0.0,
    "chaos injector: probability a network request stalls for "
    "CHAOS_STALL_S then times out (0 = off)", ptype=float)
CHAOS_STALL_S = config.register(
    "MMLSPARK_TPU_CHAOS_STALL_S", 30.0,
    "chaos injector: stalled-read duration (spent on the resilience "
    "clock, so virtual under tests)", ptype=float)
CHAOS_TORN_CKPT_RATE = config.register(
    "MMLSPARK_TPU_CHAOS_TORN_CKPT_RATE", 0.0,
    "chaos injector: probability a freshly written checkpoint is torn "
    "(truncated) after the fact (0 = off)", ptype=float)
CHAOS_PREEMPT_AT_STEP = config.register(
    "MMLSPARK_TPU_CHAOS_PREEMPT_AT_STEP", 0,
    "chaos injector: deliver one simulated SIGTERM when training reaches "
    "this global step (0 = off)", ptype=int)
CHAOS_NAN_AT_STEP = config.register(
    "MMLSPARK_TPU_CHAOS_NAN_AT_STEP", 0,
    "chaos injector: poison one training step's loss mask with NaN when "
    "training reaches this global step (0 = off) — the numerics-probe / "
    "halt_on_nonfinite drill (observe/numerics.py)", ptype=int)


class InjectedNetworkError(ConnectionError):
    """A chaos-injected connection failure (retryable by classification)."""


class InjectedStallError(TimeoutError):
    """A chaos-injected stalled read that hit its timeout."""


class ChaosInjector:
    """One seeded fault source; `get_injector()` holds the process instance."""

    def __init__(self, seed: Optional[int] = None,
                 net_error_rate: Optional[float] = None,
                 stall_rate: Optional[float] = None,
                 stall_s: Optional[float] = None,
                 torn_ckpt_rate: Optional[float] = None,
                 preempt_at_step: Optional[int] = None,
                 nan_at_step: Optional[int] = None):
        read = lambda explicit, var, cast: cast(
            var.current() if explicit is None else explicit)
        self.net_error_rate = read(net_error_rate, CHAOS_NET_ERROR_RATE, float)
        self.stall_rate = read(stall_rate, CHAOS_STALL_RATE, float)
        self.stall_s = read(stall_s, CHAOS_STALL_S, float)
        self.torn_ckpt_rate = read(torn_ckpt_rate, CHAOS_TORN_CKPT_RATE, float)
        self.preempt_at_step = read(preempt_at_step, CHAOS_PREEMPT_AT_STEP, int)
        self.nan_at_step = read(nan_at_step, CHAOS_NAN_AT_STEP, int)
        self._rng = random.Random(read(seed, CHAOS_SEED, int))
        self._preempt_fired = False
        self._nan_fired = False

    @property
    def active(self) -> bool:
        return bool(self.net_error_rate or self.stall_rate
                    or self.torn_ckpt_rate or self.preempt_at_step
                    or self.nan_at_step)

    # -- network hazards -------------------------------------------------
    def on_request(self, url: str) -> None:
        """Called before a network fetch; may raise an injected fault."""
        if self.net_error_rate and self._rng.random() < self.net_error_rate:
            inc_counter("chaos.net_errors")
            trace_event("chaos.net_error", cat="resilience", url=url)
            raise InjectedNetworkError(
                f"chaos: injected connection error for {url}")
        if self.stall_rate and self._rng.random() < self.stall_rate:
            inc_counter("chaos.stalls")
            trace_event("chaos.stall", cat="resilience", url=url,
                        stall_s=self.stall_s)
            get_clock().sleep(self.stall_s)  # virtual under tests
            raise InjectedStallError(
                f"chaos: injected {self.stall_s:.0f}s stalled read for {url}")

    # -- checkpoint hazards ----------------------------------------------
    @staticmethod
    def tear_file(path: str, keep_fraction: float = 0.5) -> None:
        """Truncate `path` in place — a torn write/partial upload."""
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, int(size * keep_fraction)))
        inc_counter("chaos.torn_files")
        trace_event("chaos.torn_file", cat="resilience", path=path)
        get_logger("resilience").warning("chaos: tore file %s", path)

    def maybe_tear_checkpoint(self, path: str) -> bool:
        if self.torn_ckpt_rate and self._rng.random() < self.torn_ckpt_rate:
            self.tear_file(path)
            return True
        return False

    # -- preemption -------------------------------------------------------
    def on_step(self, step: int) -> None:
        """Deliver the configured one-shot SIGTERM when `step` arrives.

        Uses a real signal (not a flag) so the SAME handler path that a
        cloud preemption notice exercises is the one under test.
        """
        if (self.preempt_at_step and not self._preempt_fired
                and step >= self.preempt_at_step):
            self._preempt_fired = True
            inc_counter("chaos.preemptions")
            trace_event("chaos.preemption", cat="resilience", step=step)
            get_logger("resilience").warning(
                "chaos: raising simulated SIGTERM at step %d", step)
            signal.raise_signal(signal.SIGTERM)

    # -- numerics hazards --------------------------------------------------
    def poison_nan(self, step: int) -> bool:
        """True exactly once, when `step` reaches the configured NaN
        injection point; the trainer then multiplies the step's loss mask
        by NaN (dtype-agnostic — poisons float and token models alike),
        so loss, gradients, and the updated params all go non-finite —
        the drill the numerics probe and halt_on_nonfinite exist for."""
        if (self.nan_at_step and not self._nan_fired
                and step >= self.nan_at_step):
            self._nan_fired = True
            inc_counter("chaos.nan_injections")
            trace_event("chaos.nan_injection", cat="resilience", step=step)
            get_logger("resilience").warning(
                "chaos: poisoning step %d loss mask with NaN", step)
            return True
        return False


_injector: Optional[ChaosInjector] = None


def get_injector() -> ChaosInjector:
    """The process injector, built lazily from the CHAOS_* config."""
    global _injector
    if _injector is None:
        _injector = ChaosInjector()
    return _injector


def reset_chaos() -> None:
    """Rebuild the injector from current config on next use (tests call
    this after flipping CHAOS_* variables)."""
    global _injector
    _injector = None
