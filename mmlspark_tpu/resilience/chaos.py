"""Deterministic fault injection: the test harness for the resilience layer.

A resilience subsystem that is only ever exercised by real outages is
untested code.  `ChaosInjector` is a seeded, config-driven fault source
that the I/O and training layers consult at their hazard points:

  * `on_request(url)`   — before a network fetch: may raise a connection
    error or a (virtual-clock) stalled-read timeout;
  * `on_step(step)`     — per training step: may deliver one simulated
    SIGTERM preemption at a configured step;
  * `maybe_hang(step)`  — inside the (watchdog-bounded) step execution:
    may stall for CHAOS_HANG_S real seconds, the hung-device drill;
  * `tear_file(path)`   — truncates a file in place, simulating a torn
    checkpoint from a crash or partial upload;
  * `maybe_tear_checkpoint(path)` — probabilistic form of the same, hooked
    into checkpoint rotation (target payload, sha256 sidecar, or the
    LATEST pointer);
  * `after_checkpoint_write(path)` — scripted post-rotation tears that
    must survive prune (scenario runner).

Determinism: one `random.Random(seed)` drives every probabilistic
decision, so a given seed + call sequence produces the SAME fault
pattern on every run — chaos tests are exactly reproducible, never
flaky-by-design.  Everything is off (zero rates, no seed needed) unless
the MMLSPARK_TPU_CHAOS_* variables turn it on.

**Scenario DSL**: `Scenario(name, faults=[Fault(...)], expect={...})`
declares a multi-fault script (e.g. NaN at step 30 + SIGTERM at step 45
+ a torn checkpoint on the 2nd rotation) with expected-outcome
assertions; `run_scenario(scenario, run_fn)` installs the script, runs
the workload, and checks `expect` against the observation dict `run_fn`
returns (`min_`/`max_` prefixes give bounds, anything else is an exact
match).  `make chaos-drill` runs the built-in scenario suite end-to-end
(scripts/chaos_drill.py).
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import time
from typing import Callable, Optional, Sequence

from mmlspark_tpu import config
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import trace_event
from mmlspark_tpu.resilience.clock import get_clock

CHAOS_SEED = config.register(
    "MMLSPARK_TPU_CHAOS_SEED", 0,
    "chaos injector: RNG seed (fault patterns are a pure function of "
    "seed + call order)", ptype=int)
CHAOS_NET_ERROR_RATE = config.register(
    "MMLSPARK_TPU_CHAOS_NET_ERROR_RATE", 0.0,
    "chaos injector: probability a network request raises a connection "
    "error (0 = off)", ptype=float)
CHAOS_STALL_RATE = config.register(
    "MMLSPARK_TPU_CHAOS_STALL_RATE", 0.0,
    "chaos injector: probability a network request stalls for "
    "CHAOS_STALL_S then times out (0 = off)", ptype=float)
CHAOS_STALL_S = config.register(
    "MMLSPARK_TPU_CHAOS_STALL_S", 30.0,
    "chaos injector: stalled-read duration (spent on the resilience "
    "clock, so virtual under tests)", ptype=float)
CHAOS_TORN_CKPT_RATE = config.register(
    "MMLSPARK_TPU_CHAOS_TORN_CKPT_RATE", 0.0,
    "chaos injector: probability a freshly written checkpoint is torn "
    "(truncated) after the fact (0 = off)", ptype=float)
CHAOS_PREEMPT_AT_STEP = config.register(
    "MMLSPARK_TPU_CHAOS_PREEMPT_AT_STEP", 0,
    "chaos injector: deliver one simulated SIGTERM when training reaches "
    "this global step (0 = off)", ptype=int)
CHAOS_NAN_AT_STEP = config.register(
    "MMLSPARK_TPU_CHAOS_NAN_AT_STEP", 0,
    "chaos injector: poison one training step's loss mask with NaN when "
    "training reaches this global step (0 = off) — the numerics-probe / "
    "halt_on_nonfinite drill (observe/numerics.py)", ptype=int)
CHAOS_HANG_AT_STEP = config.register(
    "MMLSPARK_TPU_CHAOS_HANG_AT_STEP", 0,
    "chaos injector: stall one training step for CHAOS_HANG_S real "
    "seconds when training reaches this global step (0 = off) — the "
    "hung-device drill the step watchdog exists for "
    "(TrainerConfig.step_timeout_s)", ptype=int)
CHAOS_HANG_S = config.register(
    "MMLSPARK_TPU_CHAOS_HANG_S", 30.0,
    "chaos injector: hung-step stall duration in REAL seconds (the "
    "watchdog races a wall-clock deadline, so this one hazard cannot "
    "ride the virtual clock)", ptype=float)
CHAOS_TORN_CKPT_TARGET = config.register(
    "MMLSPARK_TPU_CHAOS_TORN_CKPT_TARGET", "payload",
    "chaos injector: what the torn-checkpoint fault corrupts — "
    "'payload' (truncate the msgpack), 'sidecar' (truncate the sha256), "
    "or 'latest' (truncate the LATEST pointer); restore must skip to a "
    "valid checkpoint in every case", ptype=str)


class InjectedNetworkError(ConnectionError):
    """A chaos-injected connection failure (retryable by classification)."""


class InjectedStallError(TimeoutError):
    """A chaos-injected stalled read that hit its timeout."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scripted fault in a chaos scenario.

    Training faults: 'nan' | 'sigterm' | 'hang' (fire once when training
    reaches `step`) or 'tear' (corrupt `target` on the `at_write`-th
    rotation).

    Serving faults (interpreted by the serve drill's workload driver,
    scripts/serve_drill.py, against serve/engine.py): 'burst' (inject
    `size` extra back-to-back arrivals when the workload reaches request
    index `at_request`), 'slow_client' (the at_request-th HTTP client
    connects, sends a partial request, then stalls `seconds` — the engine
    must keep serving everyone else), 'poison' (the at_request-th request
    is malformed — out-of-vocabulary tokens / impossible budget — and
    must be rejected without corrupting any neighbor).  Mid-flight
    SIGTERM drills reuse kind 'sigterm': the driver feeds request indices
    to `on_step`, so `step` doubles as a request index there.

    Replica faults (interpreted by the router drill's workload driver,
    scripts/router_drill.py, against serve/router.py fleets): they fire
    at request index `at_request` and act on fleet replica number
    `replica` — 'replica_crash' (everything in flight on it fails over),
    'replica_hang' (busy but frozen until the router's hang detector
    ejects it; recovers after `seconds` on the virtual clock),
    'replica_flap' (crash now, recover after `seconds` — the half-open
    probe re-admission drill), 'replica_slow' (tick throttled by
    `factor` — stays routable until miss evidence ejects it).

    Data-service faults (interpreted by the service worker drivers,
    data/service/dispatcher.py, during the data drill
    scripts/data_drill.py): they act on service worker number `worker`
    once that worker has produced `at_elem` elements —
    'worker_crash' (the worker dies with its split unacked; the
    dispatcher must re-dispatch it with no duplicated or dropped rows),
    'worker_slow' (the worker's production throttled by `factor` — the
    stall-evidence autoscaling drill).

    KV-handoff faults (interpreted by the handoff bus itself,
    serve/handoff.py, during the disagg drill scripts/disagg_drill.py):
    they fire on the `at_request`-th KV transfer (1-based) —
    'handoff_torn' (one page frame is bit-flipped on the wire; the
    decode side must reject it on crc32 and the request re-prefills
    byte-exact), 'handoff_stall' (the sender withholds pages for
    `seconds` VIRTUAL seconds; the per-transfer deadline must fire and
    re-queue the request), 'prefill_crash_mid_transfer' (the prefill
    replica crashes after its first page is on the wire; the remaining
    pages never arrive and the request must re-prefill elsewhere).
    """

    kind: str
    step: int = 0            # nan / sigterm / hang trigger step
    seconds: float = 0.5     # hang / slow-client stall duration (REAL s);
    #                          replica_hang/flap down-time (VIRTUAL s)
    at_write: int = 1        # tear: which checkpoint write (1-based)
    target: str = "payload"  # tear: payload | sidecar | latest
    at_request: int = 1      # serving faults: workload request index (1-based)
    size: int = 8            # burst: how many extra arrivals to inject
    replica: int = 0         # replica faults: fleet position (0-based)
    factor: float = 4.0      # replica_slow / worker_slow: throttle factor
    worker: int = 0          # data faults: service worker id (0-based)
    at_elem: int = 0         # data faults: fire once the worker has
    #                          produced this many elements

    _KINDS = ("nan", "sigterm", "hang", "tear",
              "burst", "slow_client", "poison",
              "replica_crash", "replica_hang", "replica_flap",
              "replica_slow",
              "worker_crash", "worker_slow",
              "handoff_torn", "handoff_stall", "prefill_crash_mid_transfer")
    _SERVE_KINDS = ("burst", "slow_client", "poison")
    _REPLICA_KINDS = ("replica_crash", "replica_hang", "replica_flap",
                      "replica_slow")
    _DATA_KINDS = ("worker_crash", "worker_slow")
    _HANDOFF_KINDS = ("handoff_torn", "handoff_stall",
                      "prefill_crash_mid_transfer")
    _TARGETS = ("payload", "sidecar", "latest")

    def __post_init__(self):
        if self.kind not in self._KINDS:
            raise ValueError(f"fault kind must be one of {self._KINDS}, "
                             f"got {self.kind!r}")
        if self.kind == "tear" and self.target not in self._TARGETS:
            raise ValueError(f"tear target must be one of {self._TARGETS}, "
                             f"got {self.target!r}")


class ChaosInjector:
    """One seeded fault source; `get_injector()` holds the process instance."""

    def __init__(self, seed: Optional[int] = None,
                 net_error_rate: Optional[float] = None,
                 stall_rate: Optional[float] = None,
                 stall_s: Optional[float] = None,
                 torn_ckpt_rate: Optional[float] = None,
                 preempt_at_step: Optional[int] = None,
                 nan_at_step: Optional[int] = None,
                 hang_at_step: Optional[int] = None,
                 hang_s: Optional[float] = None,
                 torn_ckpt_target: Optional[str] = None,
                 script: Optional[Sequence[Fault]] = None):
        read = lambda explicit, var, cast: cast(
            var.current() if explicit is None else explicit)
        self.net_error_rate = read(net_error_rate, CHAOS_NET_ERROR_RATE, float)
        self.stall_rate = read(stall_rate, CHAOS_STALL_RATE, float)
        self.stall_s = read(stall_s, CHAOS_STALL_S, float)
        self.torn_ckpt_rate = read(torn_ckpt_rate, CHAOS_TORN_CKPT_RATE, float)
        self.torn_ckpt_target = read(torn_ckpt_target,
                                     CHAOS_TORN_CKPT_TARGET, str)
        self.preempt_at_step = read(preempt_at_step, CHAOS_PREEMPT_AT_STEP, int)
        self.nan_at_step = read(nan_at_step, CHAOS_NAN_AT_STEP, int)
        self.hang_at_step = read(hang_at_step, CHAOS_HANG_AT_STEP, int)
        self.hang_s = read(hang_s, CHAOS_HANG_S, float)
        # the declarative multi-fault script (scenario runner): each entry
        # fires at most once, latched by its index
        self.script: list[Fault] = list(script or [])
        self._fired: set = set()
        self._write_count = 0
        self._rng = random.Random(read(seed, CHAOS_SEED, int))
        self._preempt_fired = False
        self._nan_fired = False
        self._hang_fired = False

    @property
    def active(self) -> bool:
        return bool(self.net_error_rate or self.stall_rate
                    or self.torn_ckpt_rate or self.preempt_at_step
                    or self.nan_at_step or self.hang_at_step or self.script)

    def _script_due(self, kind: str, step: int) -> Optional[Fault]:
        """The first unfired scripted fault of `kind` due at `step`."""
        for i, f in enumerate(self.script):
            if f.kind == kind and i not in self._fired and step >= f.step:
                self._fired.add(i)
                return f
        return None

    # -- network hazards -------------------------------------------------
    def on_request(self, url: str) -> None:
        """Called before a network fetch; may raise an injected fault."""
        if self.net_error_rate and self._rng.random() < self.net_error_rate:
            inc_counter("chaos.net_errors")
            trace_event("chaos.net_error", cat="resilience", url=url)
            raise InjectedNetworkError(
                f"chaos: injected connection error for {url}")
        if self.stall_rate and self._rng.random() < self.stall_rate:
            inc_counter("chaos.stalls")
            trace_event("chaos.stall", cat="resilience", url=url,
                        stall_s=self.stall_s)
            get_clock().sleep(self.stall_s)  # virtual under tests
            raise InjectedStallError(
                f"chaos: injected {self.stall_s:.0f}s stalled read for {url}")

    # -- checkpoint hazards ----------------------------------------------
    @staticmethod
    def tear_file(path: str, keep_fraction: float = 0.5) -> None:
        """Truncate `path` in place — a torn write/partial upload."""
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, int(size * keep_fraction)))
        inc_counter("chaos.torn_files")
        trace_event("chaos.torn_file", cat="resilience", path=path)
        get_logger("resilience").warning("chaos: tore file %s", path)

    @classmethod
    def tear_checkpoint(cls, path: str, target: str = "payload") -> None:
        """Tear one aspect of a written checkpoint: the msgpack payload,
        its sha256 sidecar, or the directory's LATEST pointer — the three
        distinct corruption states a crash/partial upload can leave.
        Restore must skip to a valid checkpoint under ALL of them."""
        if target == "sidecar":
            cls.tear_file(path + ".sha256")
        elif target == "latest":
            from mmlspark_tpu.resilience.checkpoints import LATEST
            cls.tear_file(os.path.join(os.path.dirname(path), LATEST))
        else:
            cls.tear_file(path)

    def maybe_tear_checkpoint(self, path: str) -> bool:
        if self.torn_ckpt_rate and self._rng.random() < self.torn_ckpt_rate:
            self.tear_checkpoint(path, self.torn_ckpt_target)
            return True
        return False

    def after_checkpoint_write(self, path: str) -> bool:
        """Post-rotation hook (runs AFTER the LATEST move and prune):
        scripted scenario tears land here so the corrupt state survives
        on disk for the next restore to prove it skips it."""
        self._write_count += 1
        fault = None
        for i, f in enumerate(self.script):
            if f.kind == "tear" and i not in self._fired \
                    and self._write_count >= f.at_write:
                self._fired.add(i)
                fault = f
                break
        if fault is None:
            return False
        trace_event("chaos.torn_checkpoint", cat="resilience", path=path,
                    target=fault.target, write=self._write_count)
        self.tear_checkpoint(path, fault.target)
        return True

    # -- preemption -------------------------------------------------------
    def on_step(self, step: int) -> None:
        """Deliver the configured one-shot SIGTERM when `step` arrives.

        Uses a real signal (not a flag) so the SAME handler path that a
        cloud preemption notice exercises is the one under test.
        """
        due = self._script_due("sigterm", step) is not None
        if due or (self.preempt_at_step and not self._preempt_fired
                   and step >= self.preempt_at_step):
            if not due:
                self._preempt_fired = True
            inc_counter("chaos.preemptions")
            trace_event("chaos.preemption", cat="resilience", step=step)
            get_logger("resilience").warning(
                "chaos: raising simulated SIGTERM at step %d", step)
            signal.raise_signal(signal.SIGTERM)

    def maybe_hang(self, step: int) -> bool:
        """Stall the calling thread for `hang_s` REAL seconds, once, when
        `step` reaches the configured hang point — the hung-device drill.
        Called INSIDE the step execution the watchdog bounds
        (train/trainer.py), so the stall is observed exactly where a
        wedged collective or device would be."""
        fault = self._script_due("hang", step)
        hang_s = fault.seconds if fault is not None else self.hang_s
        due = fault is not None
        if not due and self.hang_at_step and not self._hang_fired \
                and step >= self.hang_at_step:
            self._hang_fired = True
            due = True
        if not due:
            return False
        inc_counter("chaos.hangs")
        trace_event("chaos.hang", cat="resilience", step=step,
                    hang_s=hang_s)
        get_logger("resilience").warning(
            "chaos: hanging step %d for %.2fs (real time)", step, hang_s)
        time.sleep(hang_s)  # REAL seconds: the watchdog deadline is wall
        return True

    # -- serving hazards ---------------------------------------------------
    def serve_faults_due(self, request_index: int) -> list:
        """The unfired scripted serving faults due at `request_index`
        (1-based workload position), each fired at most once.  The serve
        drill's workload driver consults this before issuing each request
        and acts the fault out — a burst enqueues `size` extra arrivals,
        a slow client stalls its connection, a poison request goes out
        malformed.  The engine under test never sees this hook; only the
        traffic it produces."""
        due = []
        for i, f in enumerate(self.script):
            if f.kind in Fault._SERVE_KINDS and i not in self._fired \
                    and request_index >= f.at_request:
                self._fired.add(i)
                inc_counter(f"chaos.serve_{f.kind}")
                trace_event(f"chaos.serve_{f.kind}", cat="resilience",
                            request_index=request_index)
                due.append(f)
        return due

    def replica_faults_due(self, request_index: int) -> list:
        """The unfired scripted REPLICA faults due at `request_index`
        (1-based workload position), each fired at most once.  The
        router drill's workload driver consults this before issuing each
        request and acts the fault out on the fleet's `Replica` handles
        (inject_crash / inject_hang / inject_slow / recover) — the
        router under test never sees this hook, only a fleet whose
        members actually fail."""
        due = []
        for i, f in enumerate(self.script):
            if f.kind in Fault._REPLICA_KINDS and i not in self._fired \
                    and request_index >= f.at_request:
                self._fired.add(i)
                inc_counter(f"chaos.{f.kind}")
                trace_event(f"chaos.{f.kind}", cat="resilience",
                            request_index=request_index, replica=f.replica)
                due.append(f)
        return due

    def handoff_faults_due(self, transfer_index: int) -> list:
        """The unfired scripted KV-HANDOFF faults due at `transfer_index`
        (1-based count of KV transfers begun), each fired at most once.
        The handoff bus (serve/handoff.py) consults this as it opens each
        transfer and acts the fault out on the wire (bit-flip a page /
        withhold pages / crash the sending replica) — the receiving side
        and the router only ever see the resulting damage."""
        due = []
        for i, f in enumerate(self.script):
            if f.kind in Fault._HANDOFF_KINDS and i not in self._fired \
                    and transfer_index >= f.at_request:
                self._fired.add(i)
                inc_counter(f"chaos.{f.kind}")
                trace_event(f"chaos.{f.kind}", cat="resilience",
                            transfer_index=transfer_index)
                due.append(f)
        return due

    # -- data-service hazards ----------------------------------------------
    def data_faults_due(self, worker: int, produced: int) -> list:
        """The unfired scripted data-service faults due for service
        `worker` once it has produced `produced` elements, each fired at
        most once.  The inproc worker driver consults this between
        elements and acts the fault out (die with the split unacked /
        throttle production); the dispatcher under test only sees the
        resulting failure."""
        due = []
        for i, f in enumerate(self.script):
            if f.kind in Fault._DATA_KINDS and i not in self._fired \
                    and f.worker == worker and produced >= f.at_elem:
                self._fired.add(i)
                inc_counter(f"chaos.{f.kind}")
                trace_event(f"chaos.{f.kind}", cat="resilience",
                            worker=worker, produced=produced)
                due.append(f)
        return due

    def data_faults_for(self, worker: int) -> list:
        """All unfired data-service faults targeting `worker`, marked
        fired — the process-mode path, where the dispatcher folds them
        into the spawned worker's environment and the fault plays out
        in that process."""
        due = []
        for i, f in enumerate(self.script):
            if f.kind in Fault._DATA_KINDS and i not in self._fired \
                    and f.worker == worker:
                self._fired.add(i)
                inc_counter(f"chaos.{f.kind}")
                trace_event(f"chaos.{f.kind}", cat="resilience",
                            worker=worker)
                due.append(f)
        return due

    # -- numerics hazards --------------------------------------------------
    def poison_nan(self, step: int) -> bool:
        """True exactly once (per configured injection), when `step`
        reaches a NaN injection point; the trainer then multiplies the
        step's loss mask by NaN (dtype-agnostic — poisons float and token
        models alike), so loss, gradients, and the updated params all go
        non-finite — the drill the numerics probe, halt_on_nonfinite, and
        the recovery supervisor exist for."""
        due = self._script_due("nan", step) is not None
        if not due and self.nan_at_step and not self._nan_fired \
                and step >= self.nan_at_step:
            self._nan_fired = True
            due = True
        if due:
            inc_counter("chaos.nan_injections")
            trace_event("chaos.nan_injection", cat="resilience", step=step)
            get_logger("resilience").warning(
                "chaos: poisoning step %d loss mask with NaN", step)
        return due


_injector: Optional[ChaosInjector] = None


def get_injector() -> ChaosInjector:
    """The process injector, built lazily from the CHAOS_* config."""
    global _injector
    if _injector is None:
        _injector = ChaosInjector()
    return _injector


def set_injector(injector: Optional[ChaosInjector]) -> Optional[ChaosInjector]:
    """Install a specific injector (the scenario runner's seam); returns
    the previous one so callers can restore it.  None = rebuild lazily
    from config on next use."""
    global _injector
    previous, _injector = _injector, injector
    return previous


def reset_chaos() -> None:
    """Rebuild the injector from current config on next use (tests call
    this after flipping CHAOS_* variables)."""
    global _injector
    _injector = None


# --------------------------------------------------------------------------
# Declarative chaos scenarios: multi-fault scripts + expected outcomes
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Scenario:
    """One declarative chaos drill: a fault script plus the outcome it
    must produce.

    `expect` keys check the observation dict the workload returns:
    `min_<k>`/`max_<k>` bound `obs[k]`; any other key is an exact match.
    Typical observation keys (see scripts/chaos_drill.py and
    tests/test_recovery.py): outcome ('completed' | 'gave_up' |
    'preempted'), steps, recoveries, finite (bool).
    """

    name: str
    faults: Sequence[Fault] = dataclasses.field(default_factory=list)
    expect: dict = dataclasses.field(default_factory=dict)


def run_scenario(scenario: Scenario, run_fn: Callable[[], dict]) -> dict:
    """Install the scenario's fault script, run the workload, check the
    expectations.

    `run_fn` owns the workload (typically a RecoverySupervisor fit) and
    returns an observation dict; this runner never raises on a failed
    expectation — it returns a machine-readable report
    `{name, passed, checks: {key: {want, got, ok}}, observed}` so a
    drill suite can run every scenario and fail at the end with the full
    picture.  The previous process injector is restored on exit.
    """
    previous = set_injector(ChaosInjector(script=list(scenario.faults)))
    trace_event("chaos.scenario_start", cat="resilience",
                scenario=scenario.name, faults=len(list(scenario.faults)))
    try:
        observed = run_fn()
    finally:
        set_injector(previous)
    checks: dict = {}
    for key, want in scenario.expect.items():
        if key.startswith("min_"):
            got = observed.get(key[4:])
            ok = got is not None and got >= want
        elif key.startswith("max_"):
            got = observed.get(key[4:])
            ok = got is not None and got <= want
        else:
            got = observed.get(key)
            ok = got == want
        checks[key] = {"want": want, "got": got, "ok": bool(ok)}
    passed = all(c["ok"] for c in checks.values())
    trace_event("chaos.scenario_end", cat="resilience",
                scenario=scenario.name, passed=passed)
    return {"name": scenario.name, "passed": passed, "checks": checks,
            "observed": observed}
