"""The KV handoff bus: fault-tolerant cache transfer between tiers.

A disaggregated fleet (docs/serving.md 'Disaggregated tiers') splits the
replicas behind one Router into a PREFILL tier and a DECODE tier.  A
prefill replica runs admission + chunked prefill only; when a cohort's
prefill finishes, the engine hands the batch to this bus instead of
seating it (`ServingEngine.handoff_export`), and each engine request
ends with status `handoff`.  The bus ships every request's finished KV
cache row — model-dtype or int8, whatever layout the tier runs — to a
decode replica as chunk-granular PAGES over the PR-14 transport framing
(`data/service/transport.py` type ``K`` frames), where it is spliced
into the resident batch by the jitted `merge_cache_rows` and decoded to
completion.  Greedy output is byte-exact with the colocated fleet: the
decode attempt replays nothing, it resumes from the exact cache rows
prefill produced.

The handoff is a first-class FAULT DOMAIN, not a best-effort copy:

  * every page frame carries (request id, page index, byte length,
    crc32) and is acked individually; a bit-flip on the wire fails the
    crc AT PARSE TIME on the decode side and nacks the transfer
  * a transfer that stops moving for `handoff_timeout_s` (virtual
    seconds) fails on the sender's watchdog; a prefill replica that
    crashes mid-transfer fails every transfer it was sending
  * ANY transfer failure re-queues the router request for re-prefill
    elsewhere under the PR-10 `RetryBudget` — the same failover path a
    replica crash takes, so a lost handoff can never amplify load
    unboundedly, and the retried request re-prefills from the prompt
    (byte-exact final output)
  * the decode side splices ONLY after the last page validates, and
    re-checks the request deadline at splice: a request whose deadline
    expired while its pages were in flight is cancelled (`kv_cancel`),
    lands a `serve.route.cancel` event in the routing timeline, and
    refunds nothing to the retry budget — it was never going to finish

Transport is real loopback TCP through the PR-14 helpers (the only
module allowed raw sockets), but both endpoints of every link are
pumped from the Router's single scheduler pass (`pump()`), so the whole
protocol — sends, acks, stalls, timeouts — runs under a `VirtualClock`
with zero sleeps, and page pushes are PIPELINED behind the prefill
tier's compute: up to `handoff_pages_per_tick` pages move per router
tick while the next chunk prefills, which the bench's disaggregated arm
reports as transfer/compute overlap.

Chaos (`resilience/chaos.py` `_HANDOFF_KINDS`) acts on the wire itself:
`handoff_torn` bit-flips one page frame, `handoff_stall` freezes the
sender, `prefill_crash_mid_transfer` crashes the sending replica after
its first page — the receiving side only ever sees the damage.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.data.service.transport import (FrameBuffer, TransportError,
                                                 accept, connect, encode_json,
                                                 encode_page, listen,
                                                 recv_ready)
from mmlspark_tpu.models.generate import (deserialize_cache_row,
                                          serialize_cache_row)
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.telemetry import active_run
from mmlspark_tpu.observe.trace import TraceContext, trace_event
from mmlspark_tpu.resilience.breaker import CircuitOpenError
from mmlspark_tpu.resilience.chaos import get_injector
from mmlspark_tpu.serve.request import TIMEOUT


class _Endpoint:
    """One side of a handoff link: a non-blocking socket plus a
    userspace send queue (flushed until EAGAIN each pump — a full kernel
    buffer never blocks the scheduler thread) and an incremental frame
    parser for whatever the peer sent."""

    def __init__(self, sock):
        sock.setblocking(False)
        self.sock = sock
        self.buf = FrameBuffer()
        self.out = bytearray()
        self.alive = True

    def queue(self, frame: bytes) -> None:
        self.out.extend(frame)

    def flush(self) -> bool:
        """Push queued bytes until the kernel buffer fills; True when
        any moved."""
        sent = False
        while self.out and self.alive:
            try:
                n = self.sock.send(self.out)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self.alive = False
                break
            if n <= 0:
                break
            del self.out[:n]
            sent = True
        return sent

    def poll(self) -> bool:
        """Drain whatever the peer sent into the frame buffer; True when
        bytes arrived."""
        if not self.alive:
            return False
        data = recv_ready(self.sock)
        if data is None:
            self.alive = False
            return False
        if data:
            self.buf.feed(data)
            return True
        return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class _Link:
    """One prefill->decode TCP pair: the sender socket lives with the
    prefill replica, the receiver socket with the decode replica; both
    are pumped by the bus."""

    def __init__(self, prefill: str, decode: str):
        self.prefill = prefill
        self.decode = decode
        srv, port = listen()
        try:
            sock = connect("127.0.0.1", port, timeout_s=5.0)
            conn = accept(srv, timeout_s=5.0)
        finally:
            srv.close()
        if conn is None:
            sock.close()
            raise TransportError(
                f"handoff link {prefill}->{decode} failed to accept")
        self.sender = _Endpoint(sock)
        self.receiver = _Endpoint(conn)

    def close(self) -> None:
        self.sender.close()
        self.receiver.close()


class _Transfer:
    """Sender-side state for one in-flight KV handoff."""

    __slots__ = ("rid", "rr", "prefill", "decode", "probe", "bucket",
                 "lane", "pages", "bytes_total", "next_page", "acked",
                 "started", "last_activity", "stall_until", "torn_page",
                 "torn_done", "crash_after", "crash_fired")

    def __init__(self, rid, rr, prefill, decode, probe, bucket, lane,
                 pages, now):
        self.rid = rid
        self.rr = rr
        self.prefill = prefill
        self.decode = decode
        self.probe = probe
        self.bucket = bucket
        self.lane = lane
        self.pages = pages
        self.bytes_total = sum(len(p) for p in pages)
        self.next_page = 0
        self.acked: set[int] = set()
        self.started = now
        self.last_activity = now
        self.stall_until = 0.0       # chaos: withhold pages until then
        self.torn_page: Optional[int] = None  # chaos: bit-flip this page
        self.torn_done = False
        self.crash_after = False     # chaos: crash sender after page 0
        self.crash_fired = False


class HandoffBus:
    """All KV transfers of one disaggregated fleet (module docstring).

    Owned by the Router; `pump()` runs inside the router's `_tick()`
    right after the replica ticks, so transfer progress, acks, splices,
    and watchdogs advance in lockstep with the scheduler — and page
    pushes overlap the prefill tier's next chunk of compute."""

    def __init__(self, router, *, timeout_s: float = 10.0,
                 pages_per_tick: int = 4):
        self._router = router
        self.timeout_s = max(1e-3, float(timeout_s))
        self.pages_per_tick = max(1, int(pages_per_tick))
        self._links: dict[tuple, _Link] = {}
        for p in router._prefill_reps:
            for d in router._decode_reps:
                self._links[(p.name, d.name)] = _Link(p.name, d.name)
        self.transfers: dict[int, _Transfer] = {}
        # decode side: partially received transfers, keyed by
        # (decode replica, router request id)
        self._partials: dict[tuple, dict] = {}
        # spliced engine requests awaiting the sender-side kv_spliced
        # handler (same process; the attempt object can't ride the wire)
        self._spliced_reqs: dict[int, tuple] = {}
        self._seq = 0                # transfers begun (chaos index, 1-based)
        self._spliced = 0
        self._retries = 0
        self._cancelled = 0
        self._bytes = 0
        self._pages = 0
        self._ticks_transfer = 0
        self._ticks_overlap = 0
        self._run = active_run()
        self._log = get_logger("serve")

    # -- accounting --------------------------------------------------------
    def _record(self, event: str, **fields) -> None:
        if self._run is not None:
            self._run.record_handoff({"event": event, **fields})
        trace_event(f"serve.handoff.{event}", cat="serve", **fields)
        inc_counter(f"serve.handoff.{event}")

    def _gauge(self) -> None:
        if self._run is None:
            return
        # exported by observe/export.py as mmlspark_tpu_handoff_{bytes,
        # inflight,retries} — the satellite's Prometheus names
        self._run.gauge("handoff.bytes", self._bytes)
        self._run.gauge("handoff.inflight", len(self.transfers))
        self._run.gauge("handoff.retries", self._retries)
        if self._ticks_transfer:
            self._run.gauge("handoff.overlap",
                            self._ticks_overlap / self._ticks_transfer)

    def stats(self) -> dict:
        return {"links": len(self._links),
                "in_flight": len(self.transfers),
                "receiving": len(self._partials),
                "transfers": self._seq,
                "spliced": self._spliced,
                "retries": self._retries,
                "cancelled_at_splice": self._cancelled,
                "bytes_sent": self._bytes,
                "pages_sent": self._pages,
                "overlap": (round(self._ticks_overlap
                                  / self._ticks_transfer, 4)
                            if self._ticks_transfer else None)}

    def transfers_from(self, prefill_name: str) -> int:
        """In-flight transfers still owed by one prefill replica (its
        SIGTERM drain waits on this reaching zero)."""
        n = sum(1 for t in self.transfers.values()
                if t.prefill == prefill_name)
        n += sum(1 for (p, d), link in self._links.items()
                 if p == prefill_name and link.sender.out)
        return n

    # -- export: the prefill engine hands a finished cohort over -----------
    def make_export(self, prefill_name: str):
        """The `ServingEngine.handoff_export` callback for one prefill
        replica (wired at Router construction)."""
        def export(*, bucket, lane, reqs, src, tok_h, caches):
            self._export(prefill_name, bucket, lane, reqs, src, tok_h,
                         caches)
        return export

    def _export(self, prefill_name, bucket, lane, reqs, src, tok_h,
                caches) -> None:
        now = self._router.now()
        rep = self._router._by_name[prefill_name]
        chunk = max(1, int(rep.engine.cfg.cache_chunk))
        for j, req in zip(src, reqs):
            rr = self._router._rr_for_attempt(req)
            if rr is None or rr.finished:
                continue
            self._begin(rr, prefill_name, bucket, lane, int(tok_h[j]),
                        caches, j, chunk, now)

    def _pick_decode(self) -> Optional[tuple]:
        """Least-loaded routable decode replica, else the first replica
        due a half-open probe (the spliced attempt IS the probe)."""
        healthy = [r for r in self._router._decode_reps if r.routable()]
        if healthy:
            return min(healthy, key=lambda r: r.load_tokens()), False
        for r in self._router._decode_reps:
            if r.probe_due():
                try:
                    r.breaker.allow()   # enter half-open for this probe
                except CircuitOpenError:
                    continue
                return r, True
        return None

    def _begin(self, rr, prefill_name, bucket, lane, first_tok, caches,
               row, chunk, now) -> None:
        self._seq += 1
        picked = self._pick_decode()
        if picked is None:
            self._record("no_decode", request=rr.id, prefill=prefill_name)
            self._router._handoff_failed(rr, "no_decode", now)
            return
        dec, probe = picked
        pages = serialize_cache_row(caches, row, chunk)
        t = _Transfer(rr.id, rr, prefill_name, dec.name, probe, bucket,
                      lane, pages, now)
        inj = get_injector()
        if inj is not None:
            for f in inj.handoff_faults_due(self._seq):
                if f.kind == "handoff_torn":
                    t.torn_page = len(pages) - 1
                elif f.kind == "handoff_stall":
                    t.stall_until = now + float(f.seconds)
                elif f.kind == "prefill_crash_mid_transfer":
                    t.crash_after = True
        self.transfers[rr.id] = t
        link = self._links[(prefill_name, dec.name)]
        header = {
            "t": "kv_begin", "req": rr.id, "from": prefill_name,
            "lane": lane, "bucket": bucket, "pages": len(pages),
            "bytes": t.bytes_total, "first_tok": first_tok,
            "max_new": rr.max_new_tokens, "deadline": rr.deadline,
            "prompt": [int(x) for x in rr.prompt.tolist()]}
        if rr.trace is not None:
            # the trace context rides the wire with the cache header: the
            # decode-side splice resumes the SAME trace id (new attempt
            # span), so the fleet waterfall shows one request end to end
            header["trace"] = rr.trace.to_wire()
        link.sender.queue(encode_json(header))
        self._record("begin", request=rr.id, prefill=prefill_name,
                     decode=dec.name, pages=len(pages),
                     bytes=t.bytes_total, probe=probe,
                     **self._router._trace_fields(rr))

    # -- the per-tick pump -------------------------------------------------
    def pump(self, now: float, compute_worked: bool = False) -> bool:
        """Advance every transfer: push pages (bounded per tick — the
        pipelining that overlaps transfer with prefill compute), deliver
        and validate on the decode side, splice completed transfers,
        drain acks, and run both watchdogs."""
        worked = False
        moving = bool(self.transfers)
        for t in list(self.transfers.values()):
            worked |= self._push_pages(t, now)
        for link in self._links.values():
            worked |= link.sender.flush()
        for link in self._links.values():
            worked |= self._pump_receiver(link, now)
        worked |= self._retry_splices(now)
        for link in self._links.values():
            worked |= link.receiver.flush()
        for link in self._links.values():
            worked |= self._pump_sender(link, now)
        worked |= self._watchdogs(now)
        if moving:
            self._ticks_transfer += 1
            if compute_worked:
                self._ticks_overlap += 1
        if worked:
            self._gauge()
        return worked

    def _push_pages(self, t: _Transfer, now: float) -> bool:
        if t.stall_until and now < t.stall_until:
            return False
        link = self._links[(t.prefill, t.decode)]
        pushed = False
        for _ in range(self.pages_per_tick):
            if t.next_page >= len(t.pages):
                break
            data = t.pages[t.next_page]
            frame = encode_page(t.rid, t.next_page, data)
            if t.torn_page == t.next_page and not t.torn_done:
                # chaos: one bit on the wire — the decode side's crc32
                # must catch it and nack the whole transfer
                t.torn_done = True
                frame = bytearray(frame)
                frame[-1] ^= 0xFF
                frame = bytes(frame)
            link.sender.queue(frame)
            t.next_page += 1
            t.last_activity = now
            self._bytes += len(data)
            self._pages += 1
            pushed = True
            if t.crash_after and not t.crash_fired:
                # chaos: the sending replica dies with its FIRST page on
                # the wire and the rest still owed; the watchdog sweep
                # fails every transfer it was sending and the requests
                # re-prefill elsewhere
                t.crash_fired = True
                self._router._by_name[t.prefill].crash(
                    "chaos: prefill crashed mid-transfer")
                break
        return pushed

    # -- decode side -------------------------------------------------------
    def _pump_receiver(self, link: _Link, now: float) -> bool:
        ep = link.receiver
        worked = ep.poll()
        while True:
            it = ep.buf.frames()
            try:
                for frame in it:
                    worked = True
                    self._on_receiver_frame(link, frame, now)
                break
            except TransportError as e:
                # a torn or corrupt page: the bad frame is already
                # consumed — nack the transfer and keep parsing
                worked = True
                rid = getattr(e, "request_id", None)
                self._record("page_rejected", request=rid,
                             decode=link.decode, error=str(e))
                if rid is not None:
                    self._partials.pop((link.decode, rid), None)
                    ep.queue(encode_json({"t": "kv_nack", "req": rid,
                                          "error": str(e)}))
        return worked

    def _retry_splices(self, now: float) -> bool:
        """A completed transfer can be waiting for a free decode slot;
        retry the splice every tick (deadline re-checked each time)."""
        worked = False
        for key in list(self._partials):
            p = self._partials.get(key)
            if p is None or not p.get("ready"):
                continue
            link = self._links.get((p["meta"]["from"], key[0]))
            if link is not None:
                worked |= self._try_splice(link, key, p, now)
        return worked

    def _on_receiver_frame(self, link: _Link, frame: tuple,
                           now: float) -> None:
        kind = frame[0]
        if kind == "json":
            msg = frame[1]
            mt = msg.get("t")
            if mt == "kv_begin":
                self._partials[(link.decode, msg["req"])] = {
                    "meta": msg, "pages": {}, "last": now, "ready": False}
            elif mt == "kv_drop":
                key = (link.decode, msg["req"])
                p = self._partials.get(key)
                if p is not None and p["meta"]["from"] == msg.get("from"):
                    del self._partials[key]
            return
        if kind != "page":
            return
        rid, idx, data = frame[1], frame[2], frame[3]
        key = (link.decode, rid)
        p = self._partials.get(key)
        if p is None:
            return                     # stale page from a dropped transfer
        p["pages"][idx] = data
        p["last"] = now
        link.receiver.queue(encode_json(
            {"t": "kv_ack", "req": rid, "page": idx}))
        if len(p["pages"]) >= int(p["meta"]["pages"]):
            p["ready"] = True
            self._try_splice(link, key, p, now)

    def _try_splice(self, link: _Link, key: tuple, p: dict,
                    now: float) -> bool:
        """All pages validated: re-check the deadline, then seat the row
        on the decode engine.  Engine backpressure (no free slot) leaves
        the transfer resident and retries next tick."""
        meta = p["meta"]
        rid = meta["req"]
        if key[1] != rid or key not in self._partials:
            return False
        if float(meta["deadline"]) <= now:
            del self._partials[key]
            link.receiver.queue(encode_json(
                {"t": "kv_cancel", "req": rid,
                 "reason": "deadline_at_splice"}))
            return True
        rep = self._router._by_name[link.decode]
        if not rep.engine.alive or rep.faulted:
            del self._partials[key]
            link.receiver.queue(encode_json(
                {"t": "kv_nack", "req": rid,
                 "error": "decode replica unavailable"}))
            return True
        try:
            caches = deserialize_cache_row(
                [p["pages"][i] for i in range(int(meta["pages"]))])
        except (ValueError, KeyError, OSError) as e:
            del self._partials[key]
            link.receiver.queue(encode_json(
                {"t": "kv_nack", "req": rid,
                 "error": f"page decode failed: {e}"}))
            return True
        # rehydrate the trace context from the wire header: the decode
        # attempt continues the SAME trace id as a new attempt span
        ctx = TraceContext.from_wire(meta.get("trace"))
        req = rep.engine.splice_remote(
            np.asarray(meta["prompt"], dtype=np.int32),
            int(meta["max_new"]), float(meta["deadline"]),
            int(meta["first_tok"]), caches,
            lane=meta.get("lane", "primary"),
            trace=None if ctx is None else ctx.child(attempt=ctx.attempt + 1))
        if req is None:
            # decode batch full; keep the pages resident and tell the
            # sender we are alive so its watchdog holds off
            p["last"] = now
            link.receiver.queue(encode_json({"t": "kv_wait", "req": rid}))
            return False
        del self._partials[key]
        self._spliced_reqs[rid] = (link.decode, req)
        link.receiver.queue(encode_json({"t": "kv_spliced", "req": rid}))
        return True

    # -- sender side: acks and outcomes ------------------------------------
    def _pump_sender(self, link: _Link, now: float) -> bool:
        ep = link.sender
        worked = ep.poll()
        while True:
            it = ep.buf.frames()
            try:
                for frame in it:
                    worked = True
                    if frame[0] == "json":
                        self._on_sender_msg(frame[1], now)
                break
            except TransportError:
                worked = True          # control channel noise; drop frame
        return worked

    def _on_sender_msg(self, msg: dict, now: float) -> None:
        mt = msg.get("t")
        rid = msg.get("req")
        t = self.transfers.get(rid)
        if mt == "kv_ack":
            if t is not None:
                t.acked.add(int(msg["page"]))
                t.last_activity = now
        elif mt == "kv_wait":
            if t is not None:
                t.last_activity = now
        elif mt == "kv_nack":
            if t is not None:
                self._fail(t, f"page_rejected: {msg.get('error', '')}",
                           now, notify_receiver=False)
        elif mt == "kv_spliced":
            self._on_spliced(rid, now)
        elif mt == "kv_cancel":
            self._on_cancel(rid, msg.get("reason", ""), now)

    def _on_spliced(self, rid: int, now: float) -> None:
        t = self.transfers.pop(rid, None)
        picked = self._spliced_reqs.pop(rid, None)
        if picked is None:
            return
        decode_name, att = picked
        rep = self._router._by_name[decode_name]
        if t is None or t.rr.finished:
            rep.engine.cancel_request(att, "fleet request already finished")
            return
        rr = t.rr
        rep.routed += 1
        att.listener = rr._notify
        rr.attempts.append((decode_name, att))
        rr._notify()
        if t.probe:
            rep.probe = att
            self._router._count("probes")
        self._spliced += 1
        wall = max(0.0, now - t.started)
        self._router.estimator.observe_handoff(t.bucket, wall)
        if self._run is not None:
            self._run.observe_hist("serve.handoff_transfer_s", wall)
            # fleet-level TTFT: the decode seat resumes with prefill's
            # first token already in hand, so splice time IS first-token
            # time for a disaggregated request
            self._run.observe_hist("serve.ttft_s", now - rr.arrival)
        self._record("splice", request=rid, prefill=t.prefill,
                     decode=decode_name, pages=len(t.pages),
                     bytes=t.bytes_total, wall_s=round(wall, 6),
                     **self._router._trace_fields(rr))
        self._router._record_routing("handoff_splice", request=rid,
                                     replica=decode_name,
                                     attempt=len(rr.attempts))

    def _on_cancel(self, rid: int, reason: str, now: float) -> None:
        """Deadline expired while the pages were in flight: the request
        is dead on arrival.  Lands a `serve.route.cancel` routing event
        and touches the retry budget NOT AT ALL — a request that could
        never finish must not spend retry tokens."""
        t = self.transfers.pop(rid, None)
        if t is None:
            return
        self._cancelled += 1
        self._record("cancel_at_splice", request=rid, prefill=t.prefill,
                     decode=t.decode, reason=reason)
        rr = t.rr
        if rr.finished:
            return
        router = self._router
        if rr in router._live:
            router._live.remove(rr)
        router._record_routing("cancel", request=rid,
                               reason=reason or "deadline_at_splice",
                               replica=t.decode)
        router._complete(rr, TIMEOUT, "deadline expired at splice")

    # -- failure / watchdogs -----------------------------------------------
    def _fail(self, t: _Transfer, reason: str, now: float,
              notify_receiver: bool = True) -> None:
        """Transfer lost: tell the receiver to drop its pages (unless
        the sender is the casualty — a dead sender sends nothing) and
        re-queue the router request for re-prefill under the retry
        budget."""
        self.transfers.pop(t.rid, None)
        self._retries += 1
        self._record("transfer_failed", request=t.rid, prefill=t.prefill,
                     decode=t.decode, reason=reason,
                     pages_sent=t.next_page, pages_acked=len(t.acked),
                     **self._router._trace_fields(t.rr))
        if notify_receiver:
            link = self._links.get((t.prefill, t.decode))
            if link is not None and link.sender.alive:
                link.sender.queue(encode_json(
                    {"t": "kv_drop", "req": t.rid, "from": t.prefill}))
        rr = t.rr
        if rr.finished:
            return
        self._router._handoff_failed(rr, reason, now)

    def _watchdogs(self, now: float) -> bool:
        worked = False
        for t in list(self.transfers.values()):
            if t.rid not in self.transfers:
                continue
            pre = self._router._by_name[t.prefill]
            dec = self._router._by_name[t.decode]
            if pre.crashed or not pre.engine.alive:
                self._fail(t, "prefill_crash", now, notify_receiver=False)
                worked = True
            elif dec.faulted or dec.draining or not dec.engine.alive:
                self._fail(t, "decode_unavailable", now)
                worked = True
            elif now - t.last_activity > self.timeout_s:
                self._fail(t, "handoff_stalled", now)
                worked = True
        horizon = 2.0 * self.timeout_s
        for key, p in list(self._partials.items()):
            if now - p["last"] > horizon:
                # orphaned pages from a sender that died silently — the
                # sender-side watchdog already re-queued the request
                del self._partials[key]
                self._record("partial_dropped", request=p["meta"]["req"],
                             decode=key[0])
                worked = True
        return worked

    # -- lifecycle ---------------------------------------------------------
    def drop_for(self, rr) -> bool:
        """Withdraw any transfer for a finished/cancelled fleet request
        (the router's drain-timeout sweep)."""
        t = self.transfers.pop(rr.id, None)
        if t is None:
            return False
        link = self._links.get((t.prefill, t.decode))
        if link is not None and link.sender.alive:
            link.sender.queue(encode_json(
                {"t": "kv_drop", "req": t.rid, "from": t.prefill}))
        self._record("transfer_dropped", request=t.rid, prefill=t.prefill,
                     decode=t.decode)
        return True

    def idle(self) -> bool:
        return (not self.transfers and not self._partials
                and not self._spliced_reqs
                and all(not l.sender.out and not l.receiver.out
                        for l in self._links.values()))

    def close(self) -> None:
        for link in self._links.values():
            link.close()
