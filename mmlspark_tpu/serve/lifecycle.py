"""Serving lifecycle: the ONE module in `serve/` that owns threads,
sockets, and signals.

Everything concurrent about the serving runtime is constructed here —
the scheduler loop thread, the HTTP front-end server, the SIGTERM ->
graceful-drain wiring (reusing the PR-1 `PreemptionGuard`).  scripts/
lint.py enforces the boundary: `threading.Thread(...)` and
`*HTTPServer(...)` constructions inside `mmlspark_tpu/serve/` are
rejected outside this file, so the engine and admission logic stay
synchronous, deterministic, and testable under a VirtualClock — policy
in one place, mechanism in another (the same split as resilience/net.py
for sockets).

Startup order is deliberate: `warmup()` pre-compiles the bucket programs
BEFORE readiness flips, so `/readyz` turning 200 means the first real
request pays zero XLA compiles; a load balancer that respects readiness
never routes traffic into a compile stall.
"""

from __future__ import annotations

import threading
from typing import Optional

from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.resilience.preemption import PreemptionGuard
from mmlspark_tpu.serve.engine import STOPPED, ServingEngine


def spawn(name: str, target) -> threading.Thread:
    """The one sanctioned thread constructor inside serve/ (see module
    docstring); daemonic so a wedged serving thread can never hold the
    interpreter's exit hostage."""
    thread = threading.Thread(target=target, daemon=True, name=name)
    thread.start()
    return thread


def start_engine(engine: ServingEngine, *,
                 install_sigterm: bool = True) -> ServingEngine:
    """Warm up (readiness flips only after every bucket program is
    compiled), wire SIGTERM -> graceful drain, and spawn the scheduler
    loop.  Returns the (now ready) engine; `engine.stop()` drains and
    joins."""
    engine.warmup()
    if install_sigterm and engine._guard is None:
        # the PR-1 guard: the handler only sets a flag; the loop checks
        # it at the next tick and starts the drain — an in-flight jitted
        # segment is never interrupted mid-dispatch.  Installation is a
        # no-op off the main thread (the guard's own rule).
        guard = PreemptionGuard(install=True)
        guard.__enter__()
        engine._guard = guard
    engine._thread = spawn("mmlspark-serve-loop", engine._loop)
    return engine


def start_router(router, *, install_sigterm: bool = True):
    """`start_engine` for a replicated fleet (serve/router.py): warm
    every replica, wire SIGTERM -> graceful drain, and spawn ONE
    scheduler thread running the router's loop — the router ticks its
    replicas serially, so the whole fleet shares the engine's
    single-scheduler determinism.  Returns the (now ready) router;
    `router.stop()` drains the fleet and joins."""
    router.warmup()
    if install_sigterm and router._guard is None:
        guard = PreemptionGuard(install=True)
        guard.__enter__()
        router._guard = guard
    router._thread = spawn("mmlspark-serve-router", router._loop)
    return router


def start_http(engine, port: int = 0, host: str = "127.0.0.1"):
    """The stdlib HTTP front end (serve/http.py handlers) on a daemon
    thread, in front of a `ServingEngine` OR a `Router` (the router
    duck-types the engine's serving surface).  Returns the
    ThreadingHTTPServer — ephemeral port readable from
    `server.server_address[1]`; stop it with
    `observe.export.stop_server(server)` (bounded wait)."""
    import http.server

    from mmlspark_tpu.serve.http import make_handler

    server = http.server.ThreadingHTTPServer(
        (host, port), make_handler(engine))
    spawn("mmlspark-serve-http", server.serve_forever)
    get_logger("serve").info("serving HTTP on %s:%d",
                             *server.server_address[:2])
    return server


def serve_forever(engine: ServingEngine, port: int = 0,
                  host: str = "127.0.0.1",
                  poll_s: float = 0.1) -> dict:
    """The blocking production entry point: start the engine + HTTP front
    end, then park until the engine drains (SIGTERM or `stop()`).
    Returns the engine's final stats.  The HTTP server is stopped with a
    bounded wait — a hung client cannot hold the exit."""
    from mmlspark_tpu.observe.export import stop_server

    start_engine(engine)
    server = start_http(engine, port, host)
    try:
        while engine.state != STOPPED:
            if engine._thread is not None:
                engine._thread.join(poll_s)
                if not engine._thread.is_alive():
                    break
    finally:
        stop_server(server)
    return engine.stats()


def stop_http(server, timeout_s: float = 2.0) -> bool:
    """Bounded-time HTTP stop (delegates to observe/export.stop_server —
    one implementation of the reaper pattern)."""
    from mmlspark_tpu.observe.export import stop_server
    return stop_server(server, timeout_s)


def drain_on_sigterm(engine: ServingEngine) -> Optional[PreemptionGuard]:
    """Install (or return the existing) SIGTERM guard for an engine that
    was started without one — inline/test setups that still want the
    mid-flight-SIGTERM drill path."""
    if engine._guard is None:
        guard = PreemptionGuard(install=True)
        guard.__enter__()
        engine._guard = guard
    return engine._guard
