"""The stdlib-only HTTP front end: request/response mapping, no policy.

Endpoints (all JSON, all dependency-free — the same zero-dependency
stance as observe/export.serve_metrics, which typically runs on the
neighboring port):

    GET  /healthz   liveness: 200 while the process can answer at all
                    (503 only once the engine has fully stopped)
    GET  /readyz    readiness: 200 only when warmup has compiled every
                    bucket program AND the engine is not draining —
                    the signal a load balancer routes on
    GET  /statz     the engine's stats dict (counts, percentiles,
                    breaker state; for a router, per-replica health
                    sections) — the drill/bench scrape surface
    POST /generate  body {"prompt": [ids], "max_new_tokens"?: n,
                    "deadline_ms"?: m} -> 200 {"tokens": [...],
                    "degraded": bool, "latency_ms": x}.
                    With "stream": true the response is chunked
                    (Transfer-Encoding: chunked) NDJSON: a {"tokens":
                    [...]} line per segment-boundary flush, a
                    {"restart": true} line when a router failover
                    bumped the stream epoch (previously streamed
                    partials are void), and a final {"done": true,
                    "status": ..., "tokens": [all]} line carrying the
                    authoritative full output.

Error mapping is the admission contract made visible: shed ->
429 + Retry-After (Overloaded.retry_after_s; a router retry-budget
shed maps the same way after admission), poison -> 400, deadline
death -> 504, drain cancellation -> 503 + Retry-After (the engine's
live `retry_after_s()` — remaining drain time, not a constant).  Every
error body is JSON with an explicit Content-Type; a client can always
machine-read why it was refused.

This module only DEFINES the handler (`make_handler(engine)`), bound to
a `ServingEngine` OR a `Router` — the router duck-types the serving
surface (submit/stats/state/ready/now/cfg/retry_after_s), so one front
end serves both.  The server itself — thread, socket — is constructed
by serve/lifecycle.py, the one module lint allows to do so.  The
handler sets a socket timeout, so a slow or hung client stalls only its
own connection thread, never the engine: its read raises, the
connection drops, everyone else keeps streaming.
"""

from __future__ import annotations

import http.server
import json
import time

from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.serve.admission import InvalidRequest, Overloaded
from mmlspark_tpu.serve.request import CANCELLED, OK, TIMEOUT
from mmlspark_tpu.serve.router import SHED

# socket timeout per connection: a hung client's read/write raises
# instead of parking a handler thread forever
CLIENT_TIMEOUT_S = 30.0

# streaming poll cadence: how long one stream_wait parks between checks
# (real seconds — streaming rides the front-end thread, never the
# scheduler)
STREAM_POLL_S = 0.05


def make_handler(engine):
    """The BaseHTTPRequestHandler subclass bound to one engine/router."""

    class ServeHandler(http.server.BaseHTTPRequestHandler):
        # HTTP/1.1 for Transfer-Encoding: chunked (streaming); every
        # non-streamed response carries Content-Length, so keep-alive
        # stays correct
        protocol_version = "HTTP/1.1"
        timeout = CLIENT_TIMEOUT_S
        error_content_type = "application/json"
        error_message_format = '{"error": "%(code)d %(message)s"}\n'

        def _json(self, code: int, payload: dict,
                  headers: dict = None) -> None:
            body = (json.dumps(payload) + "\n").encode()
            try:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                # the client vanished mid-response (hung/killed): its
                # connection is its own problem — drop it quietly rather
                # than spraying tracebacks from the handler thread
                get_logger("serve.http").debug(
                    "client gone before response (%d)", code)

        # -- health/readiness ------------------------------------------
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
            path = self.path.split("?")[0]
            if path == "/healthz":
                role = getattr(engine, "role", None) or (
                    "tiered" if getattr(engine, "tiered", False) else None)
                body = {"status": "ok", "state": engine.state}
                if role:
                    body["role"] = role
                if engine.state == "stopped":
                    self._json(503, {"status": "stopped"})
                else:
                    self._json(200, body)
            elif path == "/readyz":
                if engine.ready:
                    self._json(200, {"ready": True})
                else:
                    self._json(503, {"ready": False,
                                     "state": engine.state})
            elif path == "/statz":
                self._json(200, engine.stats())
            elif path == "/tracez":
                # live waterfall view of the run's slowest requests
                # (observe/assemble): a debug surface, so the import
                # stays lazy and a missing run degrades to an
                # explanatory payload rather than an error.  The run
                # handle comes from the engine (captured on ITS thread
                # at construction) — contextvars don't cross into the
                # server's handler threads, the explicit-handle rule
                # every worker-thread consumer in observe/ follows
                from mmlspark_tpu.observe.assemble import tracez_payload
                from mmlspark_tpu.observe.telemetry import active_run
                try:
                    top = int(self.path.split("top=")[1].split("&")[0]) \
                        if "top=" in self.path else 10
                except ValueError:
                    top = 10
                run = getattr(engine, "_run", None) or active_run()
                self._json(200, tracez_payload(run, top=top))
            else:
                self.send_error(
                    404, "unknown path "
                    "(healthz | readyz | statz | tracez | generate)")

        @staticmethod
        def _trace_headers(req, extra: dict = None) -> dict:
            """Response headers for one request: the distributed trace id
            (when tracing minted one) plus any status-specific extras —
            a client can quote X-Request-Trace to find its waterfall in
            /tracez or the run report."""
            headers = dict(extra or {})
            t = getattr(req, "trace", None)
            if t is not None:
                headers["X-Request-Trace"] = t.trace_id
            return headers

        # -- the request front end -------------------------------------
        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler contract
            if self.path.split("?")[0] != "/generate":
                self.send_error(404, "POST /generate only")
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                body = json.loads(raw.decode() or "{}")
                prompt = body["prompt"]
            except Exception as e:  # malformed request == poison: 400
                self._json(400, {"error": f"bad request body: {e}"})
                return
            deadline_ms = body.get("deadline_ms")
            try:
                req = engine.submit(
                    prompt,
                    max_new_tokens=body.get("max_new_tokens"),
                    deadline_s=(float(deadline_ms) / 1e3
                                if deadline_ms is not None else None),
                    priority=body.get("priority"))
            except InvalidRequest as e:
                self._json(400, {"error": str(e)})
                return
            except Overloaded as e:
                self._json(429, {"error": str(e), "reason": e.reason},
                           {"Retry-After":
                            f"{max(0.0, e.retry_after_s):.3f}"})
                return
            # wait past the deadline by a grace period: the boundary
            # cancel needs one segment to notice, and a just-late
            # completion should still return its tokens with the miss
            # flagged rather than a dangling connection
            budget = (max(0.0, req.deadline - engine.now())
                      + engine.cfg.drain_timeout_s + 5.0)
            if body.get("stream"):
                self._stream(req, budget)
                return
            req.wait(budget)
            if not req.finished:
                self._json(504, {"error": "request did not finish",
                                 "request": req.id},
                           self._trace_headers(req))
                return
            if req.status == OK:
                self._json(200, {
                    "tokens": list(map(int, req.tokens)),
                    "request": req.id,
                    "degraded": bool(req.degraded),
                    "met_deadline": req.finished_at <= req.deadline,
                    "latency_ms": round(req.latency_s() * 1e3, 3)},
                    self._trace_headers(req))
            elif req.status == TIMEOUT:
                self._json(504, {"error": "deadline exceeded",
                                 "request": req.id},
                           self._trace_headers(req))
            elif req.status == CANCELLED:
                self._json(503, {"error": "cancelled: engine draining",
                                 "request": req.id},
                           self._trace_headers(req, {
                               "Retry-After":
                               f"{engine.retry_after_s():.3f}"}))
            elif req.status == SHED:
                # router retry-budget exhaustion after admission: the
                # same 429 contract as front-door shedding
                self._json(429, {"error": req.detail or "shed",
                                 "reason": "retry_budget",
                                 "request": req.id},
                           self._trace_headers(req, {
                               "Retry-After":
                               f"{max(0.1, req.retry_after_s):.3f}"}))
            else:
                self._json(500, {"error": req.detail or "internal error",
                                 "request": req.id},
                           self._trace_headers(req))

        # -- token streaming -------------------------------------------
        def _chunk(self, payload: dict) -> None:
            data = (json.dumps(payload) + "\n").encode()
            self.wfile.write(f"{len(data):X}\r\n".encode()
                             + data + b"\r\n")

        def _stream(self, req, budget: float) -> None:
            """Chunked NDJSON: flush tokens as segment boundaries land
            them (`note_tokens` wakes `stream_wait`), emit a restart
            line when a failover bumps the stream epoch, then the
            authoritative final line."""
            try:
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                for k, v in self._trace_headers(req).items():
                    self.send_header(k, v)
                self.end_headers()
                start = time.monotonic()
                epoch, toks, fin = req.stream_state()
                cursor = 0
                while True:
                    e, toks, fin = req.stream_state()
                    if e != epoch:
                        self._chunk({"restart": True, "epoch": e})
                        epoch, cursor = e, 0
                    if len(toks) > cursor:
                        self._chunk({"tokens": list(
                            map(int, toks[cursor:]))})
                        cursor = len(toks)
                    if fin:
                        break
                    if time.monotonic() - start > budget:
                        break
                    req.stream_wait(epoch, cursor, timeout=STREAM_POLL_S)
                final = {"done": True,
                         "status": req.status or "incomplete",
                         "request": req.id,
                         "restarts": epoch,
                         "degraded": bool(req.degraded)}
                if req.status == OK:
                    final["tokens"] = list(map(int, req.tokens))
                    final["met_deadline"] = req.finished_at <= req.deadline
                    final["latency_ms"] = round(req.latency_s() * 1e3, 3)
                elif req.status == SHED:
                    final["retry_after_s"] = round(
                        max(0.1, req.retry_after_s), 3)
                self._chunk(final)
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError, OSError):
                get_logger("serve.http").debug(
                    "streaming client gone (request %d)", req.id)
            self.close_connection = True

        def log_message(self, fmt, *args):
            get_logger("serve.http").debug(fmt, *args)

    return ServeHandler
