"""The stdlib-only HTTP front end: request/response mapping, no policy.

Endpoints (all JSON, all dependency-free — the same zero-dependency
stance as observe/export.serve_metrics, which typically runs on the
neighboring port):

    GET  /healthz   liveness: 200 while the process can answer at all
                    (503 only once the engine has fully stopped)
    GET  /readyz    readiness: 200 only when warmup has compiled every
                    bucket program AND the engine is not draining —
                    the signal a load balancer routes on
    GET  /statz     the engine's stats dict (counts, percentiles,
                    breaker state) — the drill/bench scrape surface
    POST /generate  body {"prompt": [ids], "max_new_tokens"?: n,
                    "deadline_ms"?: m} -> 200 {"tokens": [...],
                    "degraded": bool, "latency_ms": x}

Error mapping is the admission contract made visible: shed ->
429 + Retry-After (Overloaded.retry_after_s), poison -> 400, deadline
death -> 504, drain cancellation -> 503.  Every error body is JSON with
an explicit Content-Type; a client can always machine-read why it was
refused.

This module only DEFINES the handler (`make_handler(engine)`); the
server itself — thread, socket — is constructed by serve/lifecycle.py,
the one module lint allows to do so.  The handler sets a socket timeout,
so a slow or hung client stalls only its own connection thread, never
the engine: its read raises, the connection drops, everyone else keeps
streaming.
"""

from __future__ import annotations

import http.server
import json

from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.serve.admission import InvalidRequest, Overloaded
from mmlspark_tpu.serve.engine import ServingEngine
from mmlspark_tpu.serve.request import CANCELLED, OK, TIMEOUT

# socket timeout per connection: a hung client's read/write raises
# instead of parking a handler thread forever
CLIENT_TIMEOUT_S = 30.0


def make_handler(engine: ServingEngine):
    """The BaseHTTPRequestHandler subclass bound to one engine."""

    class ServeHandler(http.server.BaseHTTPRequestHandler):
        timeout = CLIENT_TIMEOUT_S
        error_content_type = "application/json"
        error_message_format = '{"error": "%(code)d %(message)s"}\n'

        def _json(self, code: int, payload: dict,
                  headers: dict = None) -> None:
            body = (json.dumps(payload) + "\n").encode()
            try:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                # the client vanished mid-response (hung/killed): its
                # connection is its own problem — drop it quietly rather
                # than spraying tracebacks from the handler thread
                get_logger("serve.http").debug(
                    "client gone before response (%d)", code)

        # -- health/readiness ------------------------------------------
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
            path = self.path.split("?")[0]
            if path == "/healthz":
                if engine.state == "stopped":
                    self._json(503, {"status": "stopped"})
                else:
                    self._json(200, {"status": "ok",
                                     "state": engine.state})
            elif path == "/readyz":
                if engine.ready:
                    self._json(200, {"ready": True})
                else:
                    self._json(503, {"ready": False,
                                     "state": engine.state})
            elif path == "/statz":
                self._json(200, engine.stats())
            else:
                self.send_error(404, "unknown path "
                                "(healthz | readyz | statz | generate)")

        # -- the request front end -------------------------------------
        def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler contract
            if self.path.split("?")[0] != "/generate":
                self.send_error(404, "POST /generate only")
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                body = json.loads(raw.decode() or "{}")
                prompt = body["prompt"]
            except Exception as e:  # malformed request == poison: 400
                self._json(400, {"error": f"bad request body: {e}"})
                return
            deadline_ms = body.get("deadline_ms")
            try:
                req = engine.submit(
                    prompt,
                    max_new_tokens=body.get("max_new_tokens"),
                    deadline_s=(float(deadline_ms) / 1e3
                                if deadline_ms is not None else None))
            except InvalidRequest as e:
                self._json(400, {"error": str(e)})
                return
            except Overloaded as e:
                self._json(429, {"error": str(e), "reason": e.reason},
                           {"Retry-After":
                            f"{max(0.0, e.retry_after_s):.3f}"})
                return
            # wait past the deadline by a grace period: the boundary
            # cancel needs one segment to notice, and a just-late
            # completion should still return its tokens with the miss
            # flagged rather than a dangling connection
            budget = max(0.0, req.deadline - engine.now())
            req.wait(budget + engine.cfg.drain_timeout_s + 5.0)
            if not req.finished:
                self._json(504, {"error": "request did not finish",
                                 "request": req.id})
                return
            if req.status == OK:
                self._json(200, {
                    "tokens": list(map(int, req.tokens)),
                    "request": req.id,
                    "degraded": bool(req.degraded),
                    "met_deadline": req.finished_at <= req.deadline,
                    "latency_ms": round(req.latency_s() * 1e3, 3)})
            elif req.status == TIMEOUT:
                self._json(504, {"error": "deadline exceeded",
                                 "request": req.id})
            elif req.status == CANCELLED:
                self._json(503, {"error": "cancelled: engine draining",
                                 "request": req.id})
            else:
                self._json(500, {"error": req.detail or "internal error",
                                 "request": req.id})

        def log_message(self, fmt, *args):
            get_logger("serve.http").debug(fmt, *args)

    return ServeHandler
