"""Cross-request radix prefix KV cache: trie + LRU pool + lease pins.

At scale, chat traffic is zipf-distributed — shared system prompts,
few-shot templates, and multi-turn re-sends mean most arriving prompt
tokens were already prefilled by an earlier request.  This pool lets the
serving engine keep those finished prefill rows resident and splice them
into new requests so only the novel suffix is prefilled (ROADMAP open
item 1; docs/serving.md "Prefix reuse & priority lanes").

Layout: a radix trie at `chunk`-token granularity.  Each edge is the
blake2b digest of one chunk's int32 token bytes; each node stores the KV
cache slots for EXACTLY its own chunk (every array sliced `[:, i*C:
(i+1)*C]` on the slot axis), so a prompt sharing k chunks with a
resident prefix shares k nodes — no per-depth duplication, which is what
makes this a radix pool rather than a flat prompt->row map.  A hit walks
the trie to the deepest resident node and returns the per-chunk payloads
in order; the engine concatenates them back into one row and resumes
chunked prefill at the matched offset (chunk-aligned resume is exactly
how `prefill_chunk` already extends a cache mid-prompt).

Payloads are opaque: a tuple over layers of tuples of arrays whose axis
1 is the slot axis — both cache layouts ride through unchanged (2-tuple
model-dtype (B, W, H, dh); 4-tuple int8 with (B, W, H) scale arrays).
Int8 rows compose for free: ~4x more resident prefixes per HBM byte,
and `quantize_kv`'s round-trip idempotency (the max element maps to
exactly 127) means a stored int8 slot re-quantizes byte-identically at
resume finish.

Policies:
  * LRU over CHUNK nodes (one "row" of budget = one chunk of slots):
    every hit bumps its whole path; eviction picks the stalest
    unleased LEAF (interior nodes are pinned by their descendants —
    evicting an ancestor would orphan the child's resume path).
  * Lease pinning: `acquire` leases every node on the hit path until
    `release`, so an in-flight splice can never lose its donor slots
    mid-resume.  An insert that cannot evict (every candidate leased)
    is REFUSED, never forced — `evictions_refused` counts those.
  * First-writer-wins on insert: byte-identical greedy outputs are the
    correctness contract, so a chunk already resident is left alone
    (chunked-vs-whole prefill parity makes the bytes equal anyway).

Thread-safety: one lock around every operation — the engine loop
acquires/inserts while front-end threads scrape `stats()`.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Sequence

import numpy as np

# digest width for trie edges: 16 bytes of blake2b over the chunk's
# int32 token bytes — collision-safe at any realistic pool size
_DIGEST_SIZE = 16


def _chunk_digest(tokens: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
    return hashlib.blake2b(arr.tobytes(),
                           digest_size=_DIGEST_SIZE).digest()


def _payload_nbytes(payload) -> int:
    return sum(int(getattr(t, "nbytes", 0))
               for layer in payload for t in layer)


class _Node:
    """One resident chunk of KV slots (or the payload-less root)."""

    __slots__ = ("digest", "parent", "children", "payload", "nbytes",
                 "leases", "stamp", "depth")

    def __init__(self, digest: Optional[bytes], parent: Optional["_Node"],
                 depth: int):
        self.digest = digest
        self.parent = parent
        self.children: dict = {}
        self.payload = None
        self.nbytes = 0
        self.leases = 0
        self.stamp = 0
        self.depth = depth


class PrefixHit:
    """A leased longest-prefix match: `rows[i]` holds chunk i's cache
    slots; the lease (on every path node) holds until `release`."""

    __slots__ = ("nodes", "rows", "n_tokens")

    def __init__(self, nodes: list, rows: list, n_tokens: int):
        self.nodes = nodes
        self.rows = rows
        self.n_tokens = n_tokens


class PrefixCache:
    """LRU pool of radix-trie prefix KV rows with lease pinning."""

    def __init__(self, chunk: int, max_rows: int = 64,
                 max_bytes: Optional[int] = None):
        if chunk < 1:
            raise ValueError("chunk must be >= 1")
        if max_rows < 1:
            raise ValueError("max_rows must be >= 1")
        self.chunk = int(chunk)
        self.max_rows = int(max_rows)
        self.max_bytes = int(max_bytes) if max_bytes else None
        self._root = _Node(None, None, 0)
        self._lock = threading.Lock()
        self._clock = 0
        self._rows = 0
        self._bytes = 0
        self._hits = 0
        self._hit_tokens = 0
        self._misses = 0
        self._inserts = 0
        self._evictions = 0
        self._evictions_refused = 0

    # -- lookup ----------------------------------------------------------
    def acquire(self, tokens, limit: Optional[int] = None
                ) -> Optional[PrefixHit]:
        """Longest resident prefix of `tokens`, leased.  `limit` caps the
        matchable token count (the engine passes the largest chunk
        multiple strictly inside the prompt, so the resumed prefill
        always recomputes the last prompt position's logits).  Returns
        None — and counts a miss — when not even one chunk matches."""
        arr = np.asarray(tokens, dtype=np.int32).reshape(-1)
        n = len(arr) if limit is None else min(len(arr), int(limit))
        with self._lock:
            node, path = self._root, []
            for i in range(n // self.chunk):
                digest = _chunk_digest(
                    arr[i * self.chunk:(i + 1) * self.chunk])
                child = node.children.get(digest)
                if child is None:
                    break
                path.append(child)
                node = child
            if not path:
                self._misses += 1
                return None
            self._hits += 1
            self._hit_tokens += len(path) * self.chunk
            self._clock += 1
            for nd in path:
                nd.leases += 1
                nd.stamp = self._clock
            return PrefixHit(path, [nd.payload for nd in path],
                             len(path) * self.chunk)

    def release(self, hit: PrefixHit) -> None:
        """Drop the hit's lease (idempotence is the caller's problem:
        release exactly once, after the splice lands or is abandoned)."""
        with self._lock:
            for nd in hit.nodes:
                nd.leases = max(0, nd.leases - 1)

    # -- insert / evict --------------------------------------------------
    def insert(self, tokens, n_tokens: int, row: Sequence) -> dict:
        """Store the first `n_tokens` slots of `row` (a finished prefill
        cache row, slot axis 1) under the prompt's chunk path.
        `n_tokens` must be a chunk multiple strictly inside the real
        prompt.  Returns {"inserted", "evicted", "refused"} — refused
        means an eviction was needed but every candidate was leased (or
        on the insert path), so deeper chunks were skipped."""
        arr = np.asarray(tokens, dtype=np.int32).reshape(-1)
        n = min(int(n_tokens), len(arr))
        inserted = evicted = 0
        refused = False
        with self._lock:
            node, path = self._root, []
            for i in range(n // self.chunk):
                digest = _chunk_digest(
                    arr[i * self.chunk:(i + 1) * self.chunk])
                child = node.children.get(digest)
                if child is None:
                    payload = tuple(
                        tuple(t[:, i * self.chunk:(i + 1) * self.chunk]
                              for t in layer)
                        for layer in row)
                    nb = _payload_nbytes(payload)
                    freed = self._make_room(nb, protect=path)
                    if freed is None:
                        refused = True
                        self._evictions_refused += 1
                        break
                    evicted += freed
                    child = _Node(digest, node, node.depth + 1)
                    child.payload = payload
                    child.nbytes = nb
                    node.children[digest] = child
                    self._rows += 1
                    self._bytes += nb
                    self._inserts += 1
                    inserted += 1
                self._clock += 1
                child.stamp = self._clock
                path.append(child)
                node = child
        return {"inserted": inserted, "evicted": evicted,
                "refused": refused}

    def _make_room(self, nbytes: int, protect: list) -> Optional[int]:
        """Evict stale leaves until one more `nbytes` chunk fits; None =
        refused (a needed victim was leased or protected).  Caller holds
        the lock."""
        freed = 0
        guard = {id(nd) for nd in protect}
        while (self._rows + 1 > self.max_rows
               or (self.max_bytes is not None
                   and self._bytes + nbytes > self.max_bytes)):
            victim = self._pick_victim(guard)
            if victim is None:
                return None
            victim.parent.children.pop(victim.digest)
            self._rows -= 1
            self._bytes -= victim.nbytes
            self._evictions += 1
            freed += 1
        return freed

    def _pick_victim(self, guard: set) -> Optional[_Node]:
        """Stalest unleased leaf (interior nodes are pinned by resident
        descendants).  Caller holds the lock."""
        best = None
        stack = [self._root]
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            if (nd.payload is not None and not nd.children
                    and nd.leases == 0 and id(nd) not in guard
                    and (best is None or nd.stamp < best.stamp)):
                best = nd
        return best

    # -- fleet affinity --------------------------------------------------
    @staticmethod
    def affinity_key(tokens, chunk: int) -> str:
        """Stable hex key of the FIRST chunk of a prompt — the router
        hashes this onto a replica index so shared-prefix traffic
        concentrates on one pool instead of diluting N ways.  blake2b
        over the raw int32 bytes, never Python `hash()`: the key must
        agree across processes and restarts."""
        arr = np.ascontiguousarray(
            np.asarray(tokens, dtype=np.int32).reshape(-1)[:int(chunk)])
        return hashlib.blake2b(arr.tobytes(), digest_size=8).hexdigest()

    # -- observability ---------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            lookups = self._hits + self._misses
            leases = 0
            stack = [self._root]
            while stack:
                nd = stack.pop()
                stack.extend(nd.children.values())
                if nd.leases and nd.payload is not None:
                    leases += 1
            return {
                "chunk": self.chunk,
                "max_rows": self.max_rows,
                "max_bytes": self.max_bytes,
                "hits": self._hits,
                "hit_tokens": self._hit_tokens,
                "misses": self._misses,
                "hit_rate": self._hits / lookups if lookups else 0.0,
                "resident_rows": self._rows,
                "resident_bytes": self._bytes,
                "leased_rows": leases,
                "inserts": self._inserts,
                "evictions": self._evictions,
                "evictions_refused": self._evictions_refused,
            }
