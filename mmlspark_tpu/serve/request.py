"""The serving unit of work: one admitted request and its lifecycle.

A `Request` exists only AFTER admission (shed traffic raises
`admission.Overloaded` at `submit()` and never allocates state — the
point of shedding is that refused work costs nothing downstream).  From
admission on, the request moves through exactly one of these terminal
statuses:

    ok         completed; `tokens` holds the generation (trimmed after
               the first stop token), possibly `degraded=True` when the
               open breaker routed it to the quantized fallback bundle
    timeout    its deadline passed — cancelled at a segment boundary, or
               finished too late to count
    cancelled  the engine drained (SIGTERM / stop) before it could finish
    error      an internal failure; `detail` carries the reason
    handoff    prefill-tier engines only: the finished KV cache shipped
               to a decode replica (the fleet request is still live)

Deadlines are ABSOLUTE times on the resilience clock
(`resilience.clock.get_clock().monotonic()`), so every piece of deadline
math — admission feasibility, boundary cancellation, drain-by-deadline —
runs on a `VirtualClock` in tests with zero sleeps (the PR-1 testing
convention).  Completion is signalled through a `threading.Event`;
`wait()` is how a front-end thread parks until the scheduler finishes the
row.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

# terminal statuses
OK, TIMEOUT, CANCELLED, ERROR = "ok", "timeout", "cancelled", "error"
# terminal FOR THE PREFILL-TIER ENGINE only: the request's KV cache left
# for a decode replica over the handoff bus; the router's request stays
# open until the decode attempt finishes (serve/handoff.py owns it)
HANDOFF = "handoff"

# priority lanes (admission weighted shedding: overload costs the batch
# lane first — docs/serving.md "Prefix reuse & priority lanes")
INTERACTIVE, BATCH = "interactive", "batch"
PRIORITIES = (INTERACTIVE, BATCH)


class Request:
    """One admitted generation request (see module docstring)."""

    __slots__ = ("id", "prompt", "true_len", "bucket", "max_new_tokens",
                 "arrival", "deadline", "priority", "degraded", "tokens",
                 "status", "detail", "finished_at", "span", "trace",
                 "_event", "_progress", "listener")

    def __init__(self, req_id: int, prompt: np.ndarray, bucket: int,
                 max_new_tokens: int, arrival: float, deadline: float,
                 priority: str = INTERACTIVE):
        self.id = req_id
        self.prompt = prompt                  # (true_len,) int32
        self.true_len = int(prompt.shape[0])
        self.bucket = bucket
        self.max_new_tokens = int(max_new_tokens)
        self.arrival = float(arrival)
        self.deadline = float(deadline)
        self.priority = priority              # interactive | batch lane
        self.degraded = False
        self.tokens: list[int] = []           # emitted generation so far
        self.status: Optional[str] = None     # terminal status, None = open
        self.detail: str = ""
        self.finished_at: Optional[float] = None
        self.span = None                      # serve.request trace span
        self.trace = None                     # TraceContext (observe/trace):
        #   minted at router admission (or locally for a bare engine) and
        #   carried across every dispatch attempt and the KV handoff
        self._event = threading.Event()
        self._progress = threading.Condition()
        self.listener = None                  # optional progress callback
        #   (the router bridges attempt progress to its own request)

    @property
    def finished(self) -> bool:
        return self.status is not None

    def latency_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.arrival

    def finish(self, status: str, now: float, detail: str = "") -> None:
        """Terminal transition (scheduler thread); idempotent — the first
        status wins, so a drain cancel can never overwrite a completion."""
        if self.status is not None:
            return
        self.status = status
        self.detail = detail
        self.finished_at = now
        if self.span is not None:
            self.span.attrs.update(
                status=status, degraded=self.degraded,
                tokens=len(self.tokens),
                latency_s=round(now - self.arrival, 6))
            self.span.finish()
        self._event.set()
        self.note_tokens()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request reaches a terminal status (front-end
        threads; the scheduler never calls this).  True when finished."""
        return self._event.wait(timeout)

    # -- token streaming ---------------------------------------------------
    def note_tokens(self) -> None:
        """Wake streaming waiters (scheduler side, after a segment's
        tokens land in `tokens` or the request finishes)."""
        with self._progress:
            self._progress.notify_all()
        if self.listener is not None:
            self.listener()

    def stream_state(self) -> tuple:
        """(epoch, tokens-so-far, finished) for a chunked-response writer.
        A plain engine request never restarts, so its epoch is always 0;
        the router's request bumps the epoch on failover (the streamed-
        partial caveat in docs/serving.md)."""
        return 0, list(self.tokens), self.finished

    def stream_wait(self, epoch: int, cursor: int,
                    timeout: Optional[float] = None) -> bool:
        """Park until there are tokens past `cursor` (or the request is
        finished); True when progress is visible.  Streaming front-end
        threads call this between chunk flushes."""
        with self._progress:
            if len(self.tokens) > cursor or self.finished:
                return True
            self._progress.wait(timeout)
            return len(self.tokens) > cursor or self.finished
